"""Fleet aggregator (obs/aggregator.py): cross-node bucket-count
merging quantile-matches direct observation of the union stream (the
property average-of-percentiles fails), cursor pulls resume across peer
restarts without double-counting, exemplars ride the pull sweep from
``Histogram.observe(trace_id=)`` to the merged quantile's bucket, and
the three fleet doctor rules (straggler_node / fleet_burn_slope /
telemetry_gap) fire on their seeded pathologies and stay silent on the
healthy shape — all on virtual clocks (no sleeps, no sockets)."""

import random

import pytest

from radixmesh_tpu.obs.aggregator import (
    FleetAggregator,
    InprocPeer,
    merge_bucket_counts,
    merge_quantile,
)
from radixmesh_tpu.obs.doctor import DoctorConfig, MeshDoctor
from radixmesh_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    Histogram,
    Registry,
    get_registry,
    set_registry,
)
from radixmesh_tpu.obs.timeseries import TelemetryHistory

pytestmark = pytest.mark.quick


@pytest.fixture(autouse=True)
def fresh_registry():
    old = set_registry(Registry())
    yield
    set_registry(old)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _bucket_map(h: Histogram) -> dict:
    """``le`` string → cumulative count, the per-node wire shape the
    merge consumes."""
    return {
        h._le_str(i): float(c) for i, c in enumerate(h.bucket_counts())
    }


def _bucket_of(value: float, bounds) -> int:
    for i, ub in enumerate(bounds):
        if value <= ub:
            return i
    return len(bounds)


# ---------------------------------------------------------------------------
# merged percentiles
# ---------------------------------------------------------------------------


class TestMergeQuantile:
    def test_empty_and_zero_total(self):
        assert merge_quantile((), [], 0.99) == (0.0, None)
        assert merge_quantile((1.0,), [0.0, 0.0], 0.99) == (0.0, None)

    def test_single_node_identity(self):
        """A one-node fleet answers exactly what the node's own
        histogram answers — the merge is a no-op, not an estimate."""
        h = Histogram("radixmesh_x_seconds")
        rng = random.Random(7)
        for _ in range(500):
            h.observe(rng.lognormvariate(-4.0, 1.5))
        bounds, cum = merge_bucket_counts([_bucket_map(h)])
        for q in (0.5, 0.9, 0.99):
            est, _le = merge_quantile(bounds + (float("inf"),), cum, q)
            assert est == pytest.approx(h.quantile(q), rel=1e-9)

    @pytest.mark.parametrize("seed", range(5))
    def test_merged_matches_union_stream_property(self, seed):
        """K nodes observe disjoint streams; merging their bucket
        counts must answer the same quantile (same bucket, same
        interpolated estimate) as one histogram that saw the union
        stream directly. This is the property averaging per-node
        percentiles breaks: the skewed-node case below fails it by
        construction."""
        rng = random.Random(seed)
        k = rng.randint(2, 6)
        union = Histogram("radixmesh_u_seconds")
        per_node = []
        for node in range(k):
            h = Histogram("radixmesh_n_seconds")
            mu = rng.uniform(-6.0, -2.0)  # per-node latency regime
            for _ in range(rng.randint(20, 300)):
                v = rng.lognormvariate(mu, 1.0)
                h.observe(v)
                union.observe(v)
            per_node.append(_bucket_map(h))
        bounds, cum = merge_bucket_counts(per_node)
        assert cum[-1] == union.count
        for q in (0.5, 0.9, 0.99):
            est, _le = merge_quantile(bounds + (float("inf"),), cum, q)
            assert est == pytest.approx(union.quantile(q), rel=1e-9)

    def test_average_of_percentiles_would_lie(self):
        """One slow node out of four: the union p99 sits in the slow
        regime, but the mean of per-node p99s lands buckets below it —
        the merged answer must side with the union."""
        fast = [Histogram("radixmesh_f_seconds") for _ in range(3)]
        slow = Histogram("radixmesh_s_seconds")
        union = Histogram("radixmesh_u_seconds")
        for h in fast:
            for _ in range(100):
                h.observe(0.002)
                union.observe(0.002)
        for _ in range(100):
            slow.observe(8.0)
            union.observe(8.0)
        maps = [_bucket_map(h) for h in (*fast, slow)]
        bounds, cum = merge_bucket_counts(maps)
        est, le = merge_quantile(bounds + (float("inf"),), cum, 0.99)
        assert est == pytest.approx(union.quantile(0.99), rel=1e-9)
        avg_p99 = sum(h.quantile(0.99) for h in (*fast, slow)) / 4
        # The wrong answer is more than one bucket away from the truth;
        # the merged answer is in the truth's bucket.
        assert _bucket_of(est, DEFAULT_BUCKETS) == _bucket_of(
            union.quantile(0.99), DEFAULT_BUCKETS
        )
        assert (
            _bucket_of(avg_p99, DEFAULT_BUCKETS)
            < _bucket_of(est, DEFAULT_BUCKETS) - 1
        )

    def test_overflow_bucket_answers_largest_finite_bound(self):
        h = Histogram("radixmesh_o_seconds", buckets=(0.1, 1.0))
        for _ in range(10):
            h.observe(50.0)
        bounds, cum = merge_bucket_counts([_bucket_map(h)])
        est, le = merge_quantile(bounds + (float("inf"),), cum, 0.99)
        assert est == 1.0
        assert le == "+Inf"


# ---------------------------------------------------------------------------
# exemplars
# ---------------------------------------------------------------------------


class TestExemplars:
    def test_untraced_observation_allocates_nothing(self):
        """Tracing off = no exemplar dict, no exposition comment — the
        hot path pays one ``is not None`` test and nothing else."""
        h = Histogram("radixmesh_x_seconds")
        h.observe(0.03)
        assert h._exemplars is None
        assert h.exemplars() == {}

    def test_traced_observation_pins_bucket_exemplar(self):
        h = Histogram("radixmesh_x_seconds")
        h.observe(0.03, trace_id=0xABC)
        h.observe(0.04, trace_id=0xDEF)  # same bucket: last one wins
        ex = h.exemplars()
        assert list(ex) == ["0.05"]
        assert ex["0.05"]["trace_id"] == f"{0xDEF:#018x}"
        assert ex["0.05"]["value"] == 0.04

    def test_exposition_renders_exemplar_comment_line(self):
        reg = Registry()
        h = reg.histogram("radixmesh_x_seconds", "x")
        h.observe(0.03, trace_id=0xABC)
        text = reg.render()
        lines = [ln for ln in text.splitlines() if ln.startswith("# EXEMPLAR")]
        assert len(lines) == 1
        assert 'radixmesh_x_seconds_bucket{le="0.05"}' in lines[0]
        assert f"trace_id={0xABC:#018x}" in lines[0]
        # Comment lines stay comments: a Prometheus scraper ignores them.
        assert lines[0].startswith("# ")

    def test_registry_exemplars_keyed_like_snapshot(self):
        reg = Registry()
        h = reg.histogram("radixmesh_x_seconds", "x", ("tenant",))
        h.labels(tenant="t0").observe(0.03, trace_id=1)
        h.labels(tenant="t1").observe(0.2)  # untraced: omitted
        ex = reg.exemplars()
        assert list(ex) == ['radixmesh_x_seconds{tenant="t0"}']


# ---------------------------------------------------------------------------
# the pull sweep: cursors, restarts, node labeling
# ---------------------------------------------------------------------------


def _mk_history(clock, node="n0", interval_s=1.0):
    return TelemetryHistory(interval_s=interval_s, node=node, now=clock)


class TestPullSweep:
    def test_fold_is_node_labeled_and_cursor_advances(self):
        clock = FakeClock()
        c = get_registry().counter("radixmesh_seen_total", "x")
        hist = _mk_history(clock)
        c.inc(3)
        hist.sample()
        agg = FleetAggregator(
            peers=[InprocPeer("n0", hist)], now=clock
        )
        sweep = agg.pull_once()
        assert sweep["errors"] == 0 and sweep["points"] > 0
        q = agg.store.query(family="radixmesh_seen_total")
        assert 'radixmesh_seen_total{node="n0"}' in q["series"]
        st = agg.peer_status()["n0"]
        assert st["seq"] == 0 and st["cursor"] == 0

    def test_change_compressed_pull_never_double_counts(self):
        """Two pulls over one unchanged ring: the second sweep folds
        zero new points (the cursor, not a timestamp heuristic, is the
        dedup)."""
        clock = FakeClock()
        c = get_registry().counter("radixmesh_seen_total", "x")
        hist = _mk_history(clock)
        c.inc()
        hist.sample()
        agg = FleetAggregator(peers=[InprocPeer("n0", hist)], now=clock)
        first = agg.pull_once()
        assert first["points"] > 0
        assert agg.pull_once()["points"] == 0
        # New delta → the counter series gains exactly one point (the
        # sweep also folds the ring's changed self-metrics, so total
        # sweep points is not the right measure).
        def counter_points():
            q = agg.store.query(family="radixmesh_seen_total")
            return q["series"]['radixmesh_seen_total{node="n0"}']["points"]

        before = len(counter_points())
        c.inc()
        clock.advance(1.0)
        hist.sample()
        assert agg.pull_once()["points"] > 0
        assert len(counter_points()) == before + 1

    def test_peer_restart_rewinds_cursor_without_gaps(self):
        """A peer restart (fresh ring, per-boot seq) is detected by the
        seq-below-cursor signature: one counted reset, the new boot's
        ring re-pulled from its start, and the fleet store's view of
        the counter ends at the live value — no gap, no double count
        (the old boot's points stay under their own ingest seqs)."""
        clock = FakeClock()
        c = get_registry().counter("radixmesh_seen_total", "x")
        hist = _mk_history(clock)
        peer = InprocPeer("n0", hist)
        agg = FleetAggregator(peers=[peer], now=clock)
        c.inc()
        hist.sample()
        clock.advance(1.0)
        c.inc()
        hist.sample()
        agg.pull_once()
        assert agg.peer_status()["n0"]["seq"] == 1
        # The restart: the prior boot's ring is gone, a fresh history
        # re-snapshots the (persistent) process counters from seq 0.
        hist.close()
        peer.history = _mk_history(clock)
        clock.advance(1.0)
        peer.history.sample()
        sweep = agg.pull_once()
        st = agg.peer_status()["n0"]
        assert st["resets"] == 1
        assert st["seq"] == 0 and sweep["errors"] == 0
        pts = agg.store.query(family="radixmesh_seen_total")["series"][
            'radixmesh_seen_total{node="n0"}'
        ]["points"]
        # Boot 1 recorded 1 then 2; boot 2 re-ships the live value 2.
        assert [p[2] for p in pts] == [1.0, 2.0, 2.0]
        assert pts[-1][2] == c.value

    def test_deep_backlog_paginates_within_one_sweep(self):
        clock = FakeClock()
        c = get_registry().counter("radixmesh_seen_total", "x")
        hist = _mk_history(clock)
        for _ in range(6):
            c.inc()
            hist.sample()
            clock.advance(1.0)
        agg = FleetAggregator(
            peers=[InprocPeer("n0", hist)], now=clock, page_limit=1
        )
        agg.pull_once()
        st = agg.peer_status()["n0"]
        assert st["seq"] == 5
        with agg._lock:
            assert agg._state["n0"].pages > 1

    def test_dead_peer_is_an_error_not_a_sweep_kill(self):
        class DeadPeer:
            name = "rip"
            rank = None

            def fetch(self, since, limit):
                raise OSError("connection refused")

            def fetch_exemplars(self):
                return {}

        clock = FakeClock()
        hist = _mk_history(clock)
        hist.sample()
        agg = FleetAggregator(
            peers=[DeadPeer(), InprocPeer("n0", hist)], now=clock
        )
        sweep = agg.pull_once()
        assert sweep["errors"] == 1
        assert agg.peer_status()["rip"]["stalled_s"] is None
        assert agg.peer_status()["n0"]["seq"] == 0


# ---------------------------------------------------------------------------
# fleet SLO: merged quantiles + exemplars over the store
# ---------------------------------------------------------------------------


class TestFleetSlo:
    def test_fleet_p99_merges_across_nodes_with_exemplar(self):
        """Two nodes, distinct registries (a real fleet): the fast node
        dominates the median, the slow node owns the p99 — fleet_slo
        must report the union quantile and hand back the slow node's
        traced exemplar for the p99 bucket."""
        clock = FakeClock()
        regs, peers, hists = [], [], []
        for node, (lat, n, tid) in {
            "fast": (0.002, 200, None),
            "slow": (4.0, 30, 0xBEEF),
        }.items():
            reg = Registry()
            h = reg.histogram(
                "radixmesh_request_ttft_seconds", "x", ("tenant",)
            )
            for _ in range(n):
                h.labels(tenant="default").observe(lat, trace_id=tid)
            hist = TelemetryHistory(
                interval_s=1.0, node=node, now=clock, registry=reg
            )
            hist.sample()
            regs.append(reg)
            hists.append(hist)
            peers.append(InprocPeer(node, hist, registry=reg))
        agg = FleetAggregator(peers=peers, now=clock)
        agg.pull_once()
        slo = agg.fleet_slo()
        tb = slo["tenants"]["default"]["ttft"]
        assert tb["count"] == 230
        assert tb["nodes"] == ["fast", "slow"]
        # p50 in the fast regime, p99 in the slow node's bucket.
        assert tb["p50"] <= 0.0025
        assert tb["p99"] > 2.5
        ex = tb["p99_exemplar"]
        assert ex["node"] == "slow"
        assert ex["trace_id"] == f"{0xBEEF:#018x}"


# ---------------------------------------------------------------------------
# the fleet doctor rules
# ---------------------------------------------------------------------------


def _ingest_rank_series(agg, family, values, t=1000.0, seq=0):
    agg.store.ingest("router0", {
        "seq": seq,
        "interval_s": 1.0,
        "wall_offset": agg.store.wall_offset,
        "series": {
            f'{family}{{rank="{r}"}}': {"points": [[seq, t, v]]}
            for r, v in values.items()
        },
    })


class FakeHealthMesh:
    """The telemetry_gap verdict's gossip seam: rank → health score."""

    def __init__(self, scores):
        self.fleet = self
        self._scores = scores

    def health(self):
        return {r: {"score": s} for r, s in self._scores.items()}


class TestFleetDoctorRules:
    def test_straggler_named_by_rank(self):
        clock = FakeClock()
        agg = FleetAggregator(now=clock)
        _ingest_rank_series(
            agg, "fleet:decode_ewma_seconds",
            {4: 0.08, 5: 0.004, 0: 0.0},  # prefill's 0.0 is filtered
        )
        doc = MeshDoctor(aggregator=agg)
        report = doc.diagnose()
        f = next(
            f for f in report["findings"] if f["rule"] == "straggler_node"
        )
        assert f["evidence"]["rank"] == "4"
        assert f["evidence"]["signal"] == "decode_ewma"
        assert f["evidence"]["ratio"] == pytest.approx(20.0)
        for rule in ("straggler_node", "fleet_burn_slope", "telemetry_gap"):
            assert rule in report["rules_checked"]

    def test_straggler_silent_on_level_fleet_and_below_floor(self):
        clock = FakeClock()
        agg = FleetAggregator(now=clock)
        # Level fleet: 1.25x spread, under the 3x ratio.
        _ingest_rank_series(
            agg, "fleet:decode_ewma_seconds", {4: 0.005, 5: 0.004}
        )
        # Microsecond replication lags: 20x spread but under the floor —
        # sub-5ms "stragglers" are noise, not findings.
        _ingest_rank_series(
            agg, "fleet:replication_lag_seconds",
            {0: 0.000_05, 1: 0.001}, seq=1,
        )
        report = MeshDoctor(aggregator=agg).diagnose()
        assert not [
            f for f in report["findings"] if f["rule"] == "straggler_node"
        ]

    def test_straggler_replication_lag_signal(self):
        clock = FakeClock()
        agg = FleetAggregator(now=clock)
        _ingest_rank_series(
            agg, "fleet:replication_lag_seconds", {0: 0.9, 1: 0.01, 2: 0.02}
        )
        f = next(
            f
            for f in MeshDoctor(aggregator=agg).diagnose()["findings"]
            if f["rule"] == "straggler_node"
        )
        assert f["evidence"]["rank"] == "0"
        assert f["evidence"]["signal"] == "replication_lag"

    def _gap_fixture(self, clock):
        """Two pulled peers; then one sampler stops while the other
        keeps advancing across 12 virtual seconds of pulls."""
        live = _mk_history(clock, node="live")
        dead = _mk_history(clock, node="dead")
        get_registry().counter("radixmesh_seen_total", "x").inc()
        live.sample()
        dead.sample()
        agg = FleetAggregator(
            peers=[
                InprocPeer("live", live, rank=1),
                InprocPeer("dead", dead, rank=2),
            ],
            now=clock,
        )
        agg.pull_once()
        for _ in range(6):
            clock.advance(2.0)
            live.sample()  # the live sampler ticks on; the dead one stopped
            agg.pull_once()
        return agg

    def test_telemetry_gap_dead_node_verdict(self):
        clock = FakeClock()
        agg = self._gap_fixture(clock)
        doc = MeshDoctor(
            mesh=FakeHealthMesh({1: 0.95, 2: 0.1}), aggregator=agg
        )
        f = next(
            f
            for f in doc.diagnose()["findings"]
            if f["rule"] == "telemetry_gap"
        )
        assert f["evidence"]["peer"] == "dead"
        assert f["evidence"]["rank"] == "2"
        assert f["evidence"]["verdict"] == "node_dead"
        assert f["evidence"]["stalled_s"] >= 12.0

    def test_telemetry_gap_sampler_dead_verdict(self):
        """Gossip still scores the rank healthy → the process is alive,
        its SAMPLER died — a different pager than a dead node."""
        clock = FakeClock()
        agg = self._gap_fixture(clock)
        doc = MeshDoctor(
            mesh=FakeHealthMesh({1: 0.95, 2: 0.9}), aggregator=agg
        )
        f = next(
            f
            for f in doc.diagnose()["findings"]
            if f["rule"] == "telemetry_gap"
        )
        assert f["evidence"]["verdict"] == "sampler_dead"

    def test_telemetry_gap_silent_while_rings_advance(self):
        clock = FakeClock()
        live = _mk_history(clock, node="live")
        live.sample()
        agg = FleetAggregator(peers=[InprocPeer("live", live, rank=1)],
                              now=clock)
        agg.pull_once()
        clock.advance(2.0)
        live.sample()
        agg.pull_once()
        report = MeshDoctor(aggregator=agg).diagnose()
        assert not [
            f for f in report["findings"] if f["rule"] == "telemetry_gap"
        ]

    def test_fleet_burn_slope_fires_on_aggregated_burn(self):
        """Per-node shed counters sum across the fleet before the burn
        judgment: 10% of offered shed against a 1% budget is a 10x burn
        in both windows."""
        clock = FakeClock()
        agg = FleetAggregator(now=clock)

        def feed(seq, admitted, shed):
            agg.store.ingest("router0", {
                "seq": seq,
                "interval_s": 1.0,
                "wall_offset": agg.store.wall_offset,
                "series": {
                    'slo:admitted{tenant="t0"}': {
                        "points": [[seq, clock.t, float(admitted)]]
                    },
                    'slo:shed{tenant="t0"}': {
                        "points": [[seq, clock.t, float(shed)]]
                    },
                },
            })
            agg.pull_once()  # zero peers: the sweep just feeds burn

        feed(0, 0, 0)
        for i in range(1, 7):
            clock.advance(10.0)
            feed(i, i * 135, i * 15)  # offered 150/step, 10% shed
        report = MeshDoctor(aggregator=agg).diagnose()
        f = next(
            f
            for f in report["findings"]
            if f["rule"] == "fleet_burn_slope"
        )
        assert f["evidence"]["tenant"] == "t0"
        assert f["evidence"]["burn_fast"] == pytest.approx(10.0, rel=0.01)
        assert f["evidence"]["offered"] >= 20
