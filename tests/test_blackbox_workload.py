"""Black-box acceptance (PR 13, ``workload.run_blackbox_workload``):
the live history-backed doctor must stay silent on the healthy phase,
the hot shard's primary owner killed hard mid-zipf-storm must leave
crash-surviving dumps, and the post-mortem doctor must name the seeded
hot shard, a crash window containing the true kill time, and the
unclean-death truncation FROM THE DUMPS ALONE — with the telemetry
sampler's self-accounted overhead under 1% of the step-accounting
run."""

import os

import pytest

import bench
from radixmesh_tpu.workload import run_blackbox_workload


class TestBlackboxScenario:
    def test_postmortem_names_everything_from_the_dumps(self, tmp_path):
        res = run_blackbox_workload(
            seed=0, blackbox_dir=str(tmp_path), timeout_s=45.0
        )
        report = bench.build_blackbox_report(res)
        # Gates (validate_blackbox enforces them too; asserted directly
        # so a failure names the exact leg).
        assert bench.validate_blackbox(report) == []
        assert res["healthy"]["findings"] == []
        pm = res["postmortem"]
        assert pm["observer"]["hot_shard_named"]
        assert (
            pm["observer"]["hot_shard_evidence"]["shard"]
            == pm["expected"]["hot_shard"]
        )
        lo, hi = pm["observer"]["crash_evidence"]["window"]
        assert lo - 0.05 <= pm["expected"]["t_kill"] <= hi
        assert pm["victim"]["unclean"]
        assert pm["victim"]["truncation_named"]
        assert res["history"]["self_overhead"]["under_budget"]
        # The dumps themselves survived on disk: the victim's directory
        # holds segments only (the hard kill), the observer's a final.
        victim_dir = os.path.join(str(tmp_path), "victim")
        node_dir = os.path.join(victim_dir, os.listdir(victim_dir)[0])
        names = sorted(os.listdir(node_dir))
        assert any(n.startswith("segment-") for n in names)
        assert not any(n.startswith("final-") for n in names)

    @pytest.mark.quick
    def test_emitter_report_shape(self):
        """scripts/blackboxbench.py assembles through the same builder
        the schema tests pin — import seam only (the full run is the
        unmarked test above + the checked-in artifact)."""
        import importlib.util
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "blackboxbench",
            os.path.join(repo, "scripts", "blackboxbench.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert callable(mod.run)
        assert mod.blackbox_round() >= 13
