"""Disaggregated prefill/decode: KV handoff correctness vs the collocated
engine, wire round-trip over a Communicator, decode-side cache reuse, and
the ICI page-permute path on the 8-device virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from radixmesh_tpu.comm.inproc import InprocCommunicator, InprocHub
from radixmesh_tpu.engine import Engine, SamplingParams
from radixmesh_tpu.engine.disagg import (
    DecodeWorker,
    PrefillWorker,
    pack_handoff,
    unpack_handoff,
)
from radixmesh_tpu.models.llama import ModelConfig, init_params

PAGE = 4


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny().replace(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(7))
    return cfg, params


def make_prefill(model, **kw):
    cfg, params = model
    kw.setdefault("num_slots", 512)
    kw.setdefault("page_size", PAGE)
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 128)
    return PrefillWorker(cfg, params, **kw)


def make_decode(model, comm=None, **kw):
    cfg, params = model
    kw.setdefault("num_slots", 512)
    kw.setdefault("page_size", PAGE)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq_len", 128)
    return DecodeWorker(Engine(cfg, params, **kw), comm=comm)


def collocated_generate(model, prompts, n_new):
    cfg, params = model
    eng = Engine(cfg, params, num_slots=512, page_size=PAGE, max_batch=4,
                 max_seq_len=128)
    return eng.generate(prompts, SamplingParams(max_new_tokens=n_new))


class TestHandoff:
    def test_disagg_matches_collocated(self, model):
        rng = np.random.default_rng(11)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (9, 17, 5)]
        want = collocated_generate(model, prompts, 8)

        pw = make_prefill(model)
        dw = make_decode(model)
        reqs = [
            dw.submit(pw.prefill_handoff(p, SamplingParams(max_new_tokens=8)))
            for p in prompts
        ]
        dw.run_until_drained()
        got = [r.generated for r in reqs]
        assert got == want

    def test_wire_roundtrip(self, model):
        pw = make_prefill(model)
        pkt = pw.prefill_handoff([1, 2, 3, 4, 5, 6, 7], SamplingParams(max_new_tokens=4))
        pkt2 = unpack_handoff(pack_handoff(pkt))
        assert np.array_equal(pkt.prompt, pkt2.prompt)
        assert pkt.first_token == pkt2.first_token
        assert pkt.sampling == pkt2.sampling
        assert np.asarray(pkt.kv).dtype == np.asarray(pkt2.kv).dtype
        np.testing.assert_array_equal(np.asarray(pkt.kv), np.asarray(pkt2.kv))

    def test_handoff_over_communicator(self, model):
        InprocHub.reset_default()
        try:
            rx = InprocCommunicator("decode:0", None)
            tx = InprocCommunicator(None, "decode:0")
            dw = make_decode(model, comm=rx)
            pw = make_prefill(model)
            prompt = [3, 1, 4, 1, 5, 9, 2, 6]
            want = collocated_generate(model, [prompt], 6)[0]
            pkt = pw.prefill_handoff(prompt, SamplingParams(max_new_tokens=6))
            tx.send(pack_handoff(pkt))
            deadline = 100
            while not dw.has_work() and deadline:
                deadline -= 1
                import time

                time.sleep(0.01)
            assert dw.has_work(), "packet never arrived"
            dw.run_until_drained()
            req = dw.engine.stats
            assert req.finished == 1
        finally:
            InprocHub.reset_default()

    def test_decode_side_prefix_reuse(self, model):
        """Second handoff sharing a long prefix reuses the decode node's
        cached pages instead of rewriting shipped KV."""
        pw = make_prefill(model)
        dw = make_decode(model)
        base = list(range(1, 25))
        r1 = dw.submit(pw.prefill_handoff(base + [30], SamplingParams(max_new_tokens=3)))
        dw.run_until_drained()
        r2 = dw.submit(pw.prefill_handoff(base + [31], SamplingParams(max_new_tokens=3)))
        dw.run_until_drained()
        stats = dw.engine.stats
        assert stats.cached_tokens >= 24 // PAGE * PAGE
        # Both finished and generated the same as collocated.
        want = collocated_generate(model, [base + [30], base + [31]], 3)
        assert [r1.generated, r2.generated] == want

    def test_tail_only_handoff(self, model):
        """skip_prefix ships only the uncached tail's KV; generation is
        unchanged and the packet is smaller."""
        pw = make_prefill(model)
        dw = make_decode(model)
        base = list(range(1, 25))
        dw.submit(pw.prefill_handoff(base + [30], SamplingParams(max_new_tokens=3)))
        dw.run_until_drained()
        skip = dw.cached_prefix_len(base + [31])
        assert skip >= 24 // PAGE * PAGE
        full = pw.prefill_handoff(base + [31], SamplingParams(max_new_tokens=3))
        pkt = pw.prefill_handoff(
            base + [31], SamplingParams(max_new_tokens=3), skip_prefix=skip
        )
        assert np.asarray(pkt.kv).shape[2] == len(base) + 1 - skip
        assert len(pack_handoff(pkt)) < len(pack_handoff(full))
        r = dw.submit(pkt)
        dw.run_until_drained()
        want = collocated_generate(model, [base + [31]], 3)[0]
        assert r.generated == want
        assert dw.dropped == 0

    def test_tail_only_handoff_dropped_when_prefix_gone(self, model):
        """A tail-only packet whose advertised prefix was evicted is
        dropped loudly, not decoded from garbage."""
        pw = make_prefill(model)
        dw = make_decode(model)
        prompt = list(range(1, 20))
        pkt = pw.prefill_handoff(prompt, SamplingParams(max_new_tokens=3), skip_prefix=8)
        r = dw.submit(pkt)  # decode cache is empty: prefix never existed
        dw.run_until_drained()
        assert dw.dropped == 1
        assert r.state.value == "finished"
        assert dw.engine.stats.finished == 0  # dropped, not completed

    def test_prefill_side_prefix_reuse(self, model):
        """The prefill worker's own radix cache accelerates shared prompts."""
        pw = make_prefill(model)
        base = list(range(40, 70))
        pw.prefill_handoff(base + [1], SamplingParams(max_new_tokens=1))
        pw.prefill_handoff(base + [2], SamplingParams(max_new_tokens=1))
        assert pw.stats.cached_tokens >= len(base) // PAGE * PAGE


class TestIciTransfer:
    def test_page_permute(self):
        from radixmesh_tpu.parallel.kv_transfer import (
            make_kv_page_transfer,
            prefill_to_decode_perm,
        )
        from jax.sharding import Mesh

        devices = np.array(jax.devices()[:8])
        mesh = Mesh(devices, ("pd",))
        # 4 prefill ranks [0..3], 4 decode ranks [4..7].
        perm = prefill_to_decode_perm(4, 4)
        assert perm == [(0, 4), (1, 5), (2, 6), (3, 7)]
        transfer = make_kv_page_transfer(mesh, "pd", perm)
        # One page batch per rank: [8 shards * 2 pages, page=4, H=2, D=3]
        block = jnp.arange(8 * 2 * 4 * 2 * 3, dtype=jnp.float32).reshape(
            16, 4, 2, 3
        )
        out = np.asarray(transfer(block))
        src = np.asarray(block)
        for i in range(4):  # decode rank 4+i receives prefill rank i's shard
            np.testing.assert_array_equal(
                out[(4 + i) * 2 : (5 + i) * 2], src[i * 2 : (i + 1) * 2]
            )
        # Non-destination ranks (prefill side) hold zeros.
        np.testing.assert_array_equal(out[:8], np.zeros_like(out[:8]))

    def test_perm_validation(self):
        from radixmesh_tpu.parallel.kv_transfer import prefill_to_decode_perm

        assert prefill_to_decode_perm(2, 3) == [(0, 2), (1, 3)]
        with pytest.raises(ValueError):
            prefill_to_decode_perm(0, 2)
        # P > D cannot be one injective ppermute; must be rejected, not
        # deferred to an XLA error at trace time.
        with pytest.raises(ValueError):
            prefill_to_decode_perm(3, 2)


class TestQuantizedHandoff:
    """Int8 pools ship their exact stored representation (int8 + scales,
    4x smaller than dequantized f32) and the receiver stores it verbatim —
    no dequantize→requantize drift across the handoff."""

    def test_quant_to_quant_matches_collocated_quant(self, model):
        cfg, params = model
        rng = np.random.default_rng(12)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (9, 14)]
        ref = Engine(cfg, params, num_slots=512, page_size=PAGE, max_batch=4,
                     max_seq_len=128, kv_quant="int8")
        want = ref.generate(prompts, SamplingParams(max_new_tokens=8))

        pw = make_prefill(model, kv_quant="int8")
        dw = make_decode(model, kv_quant="int8")
        reqs = [
            dw.submit(pw.prefill_handoff(p, SamplingParams(max_new_tokens=8)))
            for p in prompts
        ]
        dw.run_until_drained()
        assert [r.generated for r in reqs] == want

    def test_quant_wire_roundtrip_preserves_ints_and_scales(self, model):
        pw = make_prefill(model, kv_quant="int8")
        pkt = pw.prefill_handoff(
            [1, 2, 3, 4, 5, 6, 7], SamplingParams(max_new_tokens=4)
        )
        assert np.asarray(pkt.kv).dtype == np.int8
        assert pkt.kv_scale is not None
        pkt2 = unpack_handoff(pack_handoff(pkt))
        np.testing.assert_array_equal(np.asarray(pkt.kv), np.asarray(pkt2.kv))
        np.testing.assert_array_equal(
            np.asarray(pkt.kv_scale), np.asarray(pkt2.kv_scale)
        )
        # int8 + f32 scales ≈ (1 + 4/D)/4 of the f32 payload a plain
        # gather would ship.
        kv_bytes = np.asarray(pkt.kv).nbytes
        assert np.asarray(pkt.kv_scale).nbytes * 4 <= kv_bytes  # D >= 16

    def test_quant_sender_fp_receiver(self, model):
        # Mixed deployment: the receiver dequantizes the shipped ints.
        pw = make_prefill(model, kv_quant="int8")
        dw = make_decode(model)
        req = dw.submit(
            pw.prefill_handoff([3, 1, 4, 1, 5, 9, 2, 6],
                               SamplingParams(max_new_tokens=6))
        )
        dw.run_until_drained()
        assert len(req.generated) == 6

    def test_fp_sender_quant_receiver(self, model):
        pw = make_prefill(model)
        dw = make_decode(model, kv_quant="int8")
        req = dw.submit(
            pw.prefill_handoff([2, 7, 1, 8, 2, 8], SamplingParams(max_new_tokens=6))
        )
        dw.run_until_drained()
        assert len(req.generated) == 6


class TestTopKHandoff:
    def test_top_k_survives_the_wire(self, model):
        pw = make_prefill(model)
        pkt = pw.prefill_handoff(
            [1, 2, 3, 4, 5],
            SamplingParams(temperature=1.3, top_k=1, max_new_tokens=4),
        )
        pkt2 = unpack_handoff(pack_handoff(pkt))
        assert pkt2.sampling.top_k == 1

    def test_disagg_top_k_one_matches_greedy(self, model):
        # k=1 at high temperature must stay greedy ACROSS the handoff.
        want = collocated_generate(model, [[7, 7, 2, 9, 1]], 6)
        pw, dw = make_prefill(model), make_decode(model)
        req = dw.submit(
            pw.prefill_handoff(
                [7, 7, 2, 9, 1],
                SamplingParams(temperature=1.3, top_k=1, max_new_tokens=6),
            )
        )
        dw.run_until_drained()
        assert req.generated == want[0]


class TestSpecOnDecodeWorker:
    def test_decode_worker_with_spec_matches_plain(self, model):
        """Speculative decoding on the decode side of a disaggregated
        deployment: greedy outputs must match a spec-off decode worker."""
        prompt = [5, 1, 5, 1, 5, 1, 5, 1]
        pw = make_prefill(model)
        dw_plain = make_decode(model)
        want = dw_plain.submit(
            pw.prefill_handoff(prompt, SamplingParams(max_new_tokens=10))
        )
        dw_plain.run_until_drained()

        pw2 = make_prefill(model)
        dw_spec = make_decode(model, spec_decode_tokens=3)
        got = dw_spec.submit(
            pw2.prefill_handoff(prompt, SamplingParams(max_new_tokens=10))
        )
        dw_spec.run_until_drained()
        assert got.generated == want.generated
        # The equality must not be vacuous: speculation actually engaged.
        assert dw_spec.engine.stats.spec_proposed > 0


class TestIciHandoff:
    """The handoff's KV moving over the ICI plane (VERDICT round-2 weak
    #5): prefill gathers on device, a ppermute relocates the page block
    to the decode rank's shard, decode admits the jax.Array directly —
    host RAM and JSON never touched."""

    @pytest.fixture(scope="class")
    def mesh(self):
        from jax.sharding import Mesh

        return Mesh(np.asarray(jax.devices()[:4]), axis_names=("dp",))

    def test_ici_move_is_device_to_device_and_lossless(self, model, mesh):
        from radixmesh_tpu.engine.disagg import IciHandoff

        prompt = list(range(1, 23))
        pre = make_prefill(model)
        chan = IciHandoff(mesh, "dp", src_rank=0, dst_rank=2, page_size=PAGE)
        pkt = pre.prefill_handoff(
            prompt, SamplingParams(max_new_tokens=6), device_kv=True
        )
        assert isinstance(pkt.kv, jax.Array)  # no host copy on gather
        moved = chan.move(pkt)
        assert isinstance(moved.kv, jax.Array)  # still on device post-move
        np.testing.assert_array_equal(np.asarray(moved.kv), np.asarray(pkt.kv))

    def test_ici_handoff_end_to_end_tokens(self, model, mesh):
        from radixmesh_tpu.engine.disagg import IciHandoff

        prompt = list(range(30, 55))
        want = collocated_generate(model, [prompt], 6)[0]
        pre = make_prefill(model)
        dec = make_decode(model)
        chan = IciHandoff(mesh, "dp", src_rank=1, dst_rank=3, page_size=PAGE)
        pkt = chan.move(
            pre.prefill_handoff(
                prompt, SamplingParams(max_new_tokens=6), device_kv=True
            )
        )
        req = dec.submit(pkt)
        dec.run_until_drained()
        assert req.output_tokens == want

    def test_ici_rank_validation(self, mesh):
        from radixmesh_tpu.engine.disagg import IciHandoff

        with pytest.raises(ValueError, match="outside axis"):
            IciHandoff(mesh, "dp", src_rank=0, dst_rank=9)


@pytest.mark.quick
class TestStagedStreamedHandoff:
    """PR 4 handoff lane: layer-block staging on the receive thread and
    the chunk-streamed wire path must generate EXACTLY what the
    monolithic packet does."""

    def _decode_with_staging(self, model, stage_layers, **kw):
        cfg, params = model
        kw.setdefault("num_slots", 512)
        kw.setdefault("page_size", PAGE)
        kw.setdefault("max_batch", 4)
        kw.setdefault("max_seq_len", 128)
        return DecodeWorker(
            Engine(cfg, params, **kw), stage_layers=stage_layers
        )

    def test_layer_staged_packet_matches_monolithic(self, model):
        pw = make_prefill(model)
        prompt = list(range(1, 60))
        sp = SamplingParams(max_new_tokens=6)
        pkt = unpack_handoff(pack_handoff(pw.prefill_handoff(prompt, sp)))
        ref = make_decode(model)
        r0 = ref.submit(pkt)
        ref.run_until_drained()
        staged = self._decode_with_staging(model, stage_layers=1)
        r1 = staged.submit(pkt)
        staged.run_until_drained()
        assert r1.generated == r0.generated

    def test_streamed_chunks_match_monolithic(self, model):
        pw = make_prefill(model)
        prompt = list(range(1, 70))
        sp = SamplingParams(max_new_tokens=6)
        ref_pkt = pw.prefill_handoff(prompt, sp)
        ref = make_decode(model)
        r0 = ref.submit(ref_pkt)
        ref.run_until_drained()

        wire: list[bytes] = []
        n = pw.prefill_handoff_stream(
            prompt, sp, send=wire.append, chunk_tokens=16
        )
        assert n == len(wire) > 1
        dw = self._decode_with_staging(model, stage_layers=0)
        for frame in wire:
            dw._on_packet(frame)
        req = dw._pending[0][0]
        dw.run_until_drained()
        assert req.generated == r0.generated

    def test_streamed_chunks_tolerate_out_of_order_delivery(self, model):
        pw = make_prefill(model)
        prompt = list(range(1, 70))
        sp = SamplingParams(max_new_tokens=4)
        ref_pkt = pw.prefill_handoff(prompt, sp)
        ref = make_decode(model)
        r0 = ref.submit(ref_pkt)
        ref.run_until_drained()

        wire: list[bytes] = []
        pw.prefill_handoff_stream(prompt, sp, send=wire.append, chunk_tokens=16)
        dw = self._decode_with_staging(model, stage_layers=0)
        for frame in reversed(wire):  # reassembly must sort by chunk_seq
            dw._on_packet(frame)
        req = dw._pending[0][0]
        dw.run_until_drained()
        assert req.generated == r0.generated

    def test_streamed_through_plane_pipeline(self, model):
        """send runs on the plane worker (pipelined with later gathers);
        the wire content must be identical to the inline loop's."""
        from radixmesh_tpu.cache.kv_transfer import KVTransferPlane

        pw = make_prefill(model)
        prompt = list(range(1, 50))
        sp = SamplingParams(max_new_tokens=4)
        inline: list[bytes] = []
        pw.prefill_handoff_stream(prompt, sp, send=inline.append, chunk_tokens=16)
        plane = KVTransferPlane(name="handoff-test")
        try:
            piped: list[bytes] = []
            done = __import__("threading").Event()
            pw.prefill_handoff_stream(
                prompt, sp, send=piped.append, chunk_tokens=16, plane=plane
            )
            plane.submit_task(done.set)  # FIFO: fires after all sends
            assert done.wait(10)
            assert len(piped) == len(inline)
            # Same chunk_of/kv_start framing and (numerically) the same
            # payloads — the second serve recomputes the non-page-aligned
            # tail token through a different compile bucket, so the last
            # chunk matches to float tolerance rather than bitwise.
            for a, b in zip(piped, inline):
                pa, pb = unpack_handoff(a), unpack_handoff(b)
                assert pa.chunk_seq == pb.chunk_seq
                assert pa.chunk_of == pb.chunk_of
                assert pa.kv_start == pb.kv_start
                np.testing.assert_allclose(
                    np.asarray(pa.kv), np.asarray(pb.kv), rtol=1e-3, atol=1e-4
                )
        finally:
            plane.close()

    def test_fully_skipped_stream_still_delivers_request(self, model):
        """skip_prefix covering the whole prompt must still SHIP the
        request as one empty-KV chunk — the receiver then resolves it
        like any over-skipped packet (admit on sufficient local reuse,
        or drop LOUDLY), instead of the stream silently sending zero
        packets and losing the request forever."""
        pw = make_prefill(model)
        prompt = list(range(1, 41))
        sp = SamplingParams(max_new_tokens=4)
        dw = make_decode(model)
        wire: list[bytes] = []
        n = pw.prefill_handoff_stream(
            prompt, sp, send=wire.append, chunk_tokens=16,
            skip_prefix=len(prompt),
        )
        assert n == len(wire) == 1  # one empty-KV chunk, not zero packets
        dw._on_packet(wire[0])
        req = dw._pending[0][0]
        dw.run_until_drained()
        # Local reuse caps below the full prompt by design, so this
        # over-skipped handoff resolves as the DOCUMENTED loud drop —
        # observable and counted, not vanished.
        assert req.state.value == "finished"
        assert dw.dropped == 1
