"""Routing-layer tests (reference ``router/cache_aware_router.py``;
routing assertions in ``test/correctness.py:57-74,95-103``)."""

import time

import numpy as np
import pytest

from radixmesh_tpu.cache.kv_pool import PagedKVPool
from radixmesh_tpu.cache.mesh_cache import MeshCache
from radixmesh_tpu.comm.inproc import InprocHub
from radixmesh_tpu.config import MeshConfig, NodeRole
from radixmesh_tpu.router import CacheAwareRouter, ConsistentHash


def wait_for(pred, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class TestConsistentHash:
    def test_deterministic(self):
        ring = ConsistentHash(["a", "b", "c"])
        key = [1, 2, 3]
        assert ring.get_node(key) == ring.get_node(key)
        assert ConsistentHash(["a", "b", "c"]).get_node(key) == ring.get_node(key)

    def test_spread(self):
        ring = ConsistentHash([f"n{i}" for i in range(4)], virtual_nodes=32)
        owners = {ring.get_node([i, i + 1]) for i in range(200)}
        assert len(owners) == 4  # every node gets some keys

    def test_remove_node_only_moves_its_keys(self):
        ring = ConsistentHash([f"n{i}" for i in range(4)], virtual_nodes=16)
        keys = [[i, 7 * i] for i in range(100)]
        before = {tuple(k): ring.get_node(k) for k in keys}
        ring.remove_node("n2")
        for k in keys:
            owner = ring.get_node(k)
            assert owner != "n2"
            if before[tuple(k)] != "n2":  # unaffected keys stay put
                assert owner == before[tuple(k)]

    def test_empty_ring(self):
        assert ConsistentHash().get_node([1]) is None

    def test_string_and_bytes_keys(self):
        ring = ConsistentHash(["a", "b"])
        assert ring.get_node("hello") in ("a", "b")
        assert ring.get_node(b"hello") in ("a", "b")


@pytest.fixture(autouse=True)
def fresh_hub():
    InprocHub.reset_default()
    yield
    InprocHub.reset_default()


@pytest.fixture
def cluster():
    prefill = ["p0", "p1"]
    decode = ["d0"]
    router = ["r0"]
    nodes = []
    for addr in prefill + decode + router:
        cfg = MeshConfig(
            prefill_nodes=prefill,
            decode_nodes=decode,
            router_nodes=router,
            local_addr=addr,
            protocol="inproc",
            tick_interval_s=0.05,
            gc_interval_s=30.0,
        )
        pool = (
            None
            if cfg.local_role is NodeRole.ROUTER
            else PagedKVPool(num_slots=128, num_layers=1, num_kv_heads=1, head_dim=2)
        )
        nodes.append(MeshCache(cfg, pool=pool).start())
    for n in nodes:
        assert n.wait_ready(timeout=10)
    yield nodes
    for n in nodes:
        n.close()


class TestCacheAwareRouter:
    def _router(self, cluster) -> CacheAwareRouter:
        node = next(n for n in cluster if n.role is NodeRole.ROUTER)
        return CacheAwareRouter(node, node.cfg)

    def test_warm_up_uses_hash_ring(self, cluster):
        router = self._router(cluster)
        key = [1, 2, 3]
        slots = cluster[1].pool.alloc(3)
        cluster[1].insert(key, slots)
        wait_for(lambda: router.mesh_cache.match_prefix(key).prefill_rank == 1)
        r = router.cache_aware_route(key)
        assert not r.prefill_cache_hit and not r.decode_cache_hit
        assert r.prefill_addr in ("p0", "p1") and r.decode_addr == "d0"

    def test_hit_routes_to_writer(self, cluster):
        router = self._router(cluster)
        router.finish_warm_up()
        key = [5, 6, 7, 8]
        slots = cluster[1].pool.alloc(4)
        cluster[1].insert(key, slots)  # prefill rank 1 writes
        assert wait_for(
            lambda: router.mesh_cache.match_prefix(key).prefill_rank == 1
        )
        r = router.cache_aware_route(key)
        assert r.prefill_cache_hit and r.prefill_addr == "p1"
        assert not r.decode_cache_hit and r.decode_addr == "d0"  # hash fallback
        assert r.match_len == 4

    def test_decode_writer_reported(self, cluster):
        router = self._router(cluster)
        router.finish_warm_up()
        key = [9, 10, 11]
        decode_node = next(n for n in cluster if n.role is NodeRole.DECODE)
        slots = decode_node.pool.alloc(3)
        decode_node.insert(key, slots)
        assert wait_for(
            lambda: router.mesh_cache.match_prefix(key).decode_rank >= 0
        )
        r = router.cache_aware_route(key)
        assert r.decode_cache_hit and r.decode_addr == "d0"

    def test_miss_routes_consistently(self, cluster):
        router = self._router(cluster)
        router.finish_warm_up()
        key = [42, 43, 44]
        r1 = router.cache_aware_route(key)
        r2 = router.cache_aware_route(key)
        assert (r1.prefill_addr, r1.decode_addr) == (r2.prefill_addr, r2.decode_addr)
        assert not r1.prefill_cache_hit

    def test_remove_node_reroutes(self, cluster):
        router = self._router(cluster)
        router.finish_warm_up()
        hit_p0 = next(
            k for k in ([i, i] for i in range(100))
            if router.cache_aware_route(k).prefill_addr == "p0"
        )
        router.remove_node("prefill", "p0")
        assert router.cache_aware_route(hit_p0).prefill_addr == "p1"

    def test_requires_router_mode(self, cluster):
        prefill_node = cluster[0]
        router = CacheAwareRouter(prefill_node, prefill_node.cfg)
        router.finish_warm_up()
        with pytest.raises(AssertionError):
            router.cache_aware_route([1, 2])


class TestOverloadShedding:
    """Hot-prefix protection: a cache hit pointing at a node whose
    estimated in-flight load is far above the role's mean takes the hash
    fallback instead — one recomputed prefix beats a convoy."""

    def _router(self, cluster, **kw) -> CacheAwareRouter:
        node = next(n for n in cluster if n.role is NodeRole.ROUTER)
        r = CacheAwareRouter(node, node.cfg, **kw)
        r.finish_warm_up()
        return r

    def _advertise(self, cluster, router, key, writer=1):
        slots = cluster[writer].pool.alloc(len(key))
        cluster[writer].insert(key, slots)
        assert wait_for(
            lambda: router.mesh_cache.match_prefix(key).prefill_rank == writer
        )

    def test_hot_prefix_sheds_past_threshold(self, cluster):
        router = self._router(cluster, overload_factor=1.5, overload_floor=5.0)
        key = [3, 1, 4]
        self._advertise(cluster, router, key)
        hot = cluster[1].cfg.prefill_addr(1)
        addrs = [router.cache_aware_route(key).prefill_addr for _ in range(60)]
        assert addrs[0] == hot  # cold: follow the cache
        assert any(a != hot for a in addrs), "overload never shed"
        # Shedding is temporary pressure relief, not a ban: the hot node
        # must receive traffic again AFTER the first shed (the shed
        # target accumulates load, pulling the ratio back down).
        first_shed = next(i for i, a in enumerate(addrs) if a != hot)
        assert any(a == hot for a in addrs[first_shed + 1 :]), (
            "hot node permanently banned after first shed"
        )

    def test_default_settings_shed_when_peers_idle(self, cluster):
        # The DEFAULT factor must be reachable (the threshold compares
        # against the OTHER nodes' mean): a hot node with an idle peer
        # sheds once the floor is crossed.
        router = self._router(cluster)  # defaults: factor 3.0, floor 8.0
        key = [6, 2, 8]
        self._advertise(cluster, router, key)
        hot = cluster[1].cfg.prefill_addr(1)
        addrs = [router.cache_aware_route(key).prefill_addr for _ in range(40)]
        assert any(a != hot for a in addrs), "default config never shed"

    def test_shed_result_reports_no_match(self, cluster):
        router = self._router(cluster, overload_factor=1.5, overload_floor=5.0)
        key = [8, 8, 3]
        self._advertise(cluster, router, key)
        hot = cluster[1].cfg.prefill_addr(1)
        shed = [
            r
            for r in (router.cache_aware_route(key) for _ in range(60))
            if r.prefill_addr != hot
        ]
        assert shed, "never shed"
        for r in shed:  # routed node lacks the prefix → no hit, no match_len
            assert not r.prefill_cache_hit
            assert r.match_len == 0

    def test_disabled_never_sheds(self, cluster):
        router = self._router(cluster, overload_factor=None)
        key = [2, 7, 1]
        self._advertise(cluster, router, key)
        hot = cluster[1].cfg.prefill_addr(1)
        assert all(
            router.cache_aware_route(key).prefill_addr == hot for _ in range(40)
        )

    def test_light_traffic_never_sheds(self, cluster):
        router = self._router(cluster, overload_factor=1.5, overload_floor=50.0)
        key = [9, 9, 1]
        self._advertise(cluster, router, key)
        hot = cluster[1].cfg.prefill_addr(1)
        assert all(
            router.cache_aware_route(key).prefill_addr == hot for _ in range(30)
        )
