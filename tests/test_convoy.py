"""Decode-interleaved chunked prefill (PR 19): engine-level proofs that
mixed compute waves change WHEN prefill runs, never WHAT is generated —
output equivalence against the legacy alternating schedule, exact chunk
resume offsets, spec-decode composition, the ``prefill_inline`` stall
attribution, draft-ahead from promoted prefixes, and the small-batch
paged dispatch seam."""

import inspect
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from radixmesh_tpu.engine import Engine, RequestState, SamplingParams
from radixmesh_tpu.models.llama import ModelConfig, init_params
from radixmesh_tpu.obs.token_timeline import STALL_CAUSES
from radixmesh_tpu.ops.attention import (
    batch_bucket,
    last_dispatch,
    paged_attention_pool,
    paged_attention_pool_bucketed,
    select_paged,
)

pytestmark = pytest.mark.quick

PAGE = 4


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny().replace(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(7))
    return cfg, params


def make_engine(model, **kw):
    cfg, params = model
    kw.setdefault("num_slots", 512)
    kw.setdefault("page_size", PAGE)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq_len", 128)
    return Engine(cfg, params, **kw)


def repetitive_prompt(n_tokens: int, seed: int, vocab: int) -> list[int]:
    head = np.random.default_rng(seed).integers(1, vocab - 1, size=4)
    return (list(map(int, head)) * ((n_tokens // 4) + 1))[:n_tokens]


def staggered_run(eng, prompts, samp, lead_steps=3, cap=600):
    """First prompt admitted and decoding, the rest arriving mid-decode
    — the arrival pattern that exposes the convoy."""
    reqs = [eng.add_request(prompts[0], samp)]
    for _ in range(lead_steps):
        eng.step()
    reqs += [eng.add_request(p, samp) for p in prompts[1:]]
    steps = 0
    while eng.has_work() and steps < cap:
        eng.step()
        steps += 1
    assert not eng.has_work(), "engine failed to drain"
    return [list(map(int, r.output_tokens)) for r in reqs]


class TestMixedWaveEquivalence:
    def test_outputs_match_legacy_schedule(self, model):
        cfg, _ = model
        prompts = [
            repetitive_prompt(n, i, cfg.vocab_size)
            for i, n in enumerate((12, 90, 9))
        ]
        samp = SamplingParams(temperature=0.0, max_new_tokens=10)
        base = staggered_run(make_engine(model), prompts, samp)
        mixed_eng = make_engine(model, prefill_inline_budget=16)
        mixed = staggered_run(mixed_eng, prompts, samp)
        assert base == mixed
        # The mixed arm actually interleaved: inline tokens advanced
        # inside decode-bearing waves, not as legacy bulk prefill.
        snap = mixed_eng.waves.snapshot()
        assert snap["inline_tokens"] > 0
        assert snap["counts"]["mixed"] > 0

    def test_spec_decode_composes_with_inline_prefill(self, model):
        cfg, _ = model
        prompts = [
            repetitive_prompt(n, 20 + i, cfg.vocab_size)
            for i, n in enumerate((16, 80, 12))
        ]
        samp = SamplingParams(temperature=0.0, max_new_tokens=12)
        base_eng = make_engine(model, spec_decode_tokens=2)
        base = staggered_run(base_eng, prompts, samp)
        mixed_eng = make_engine(
            model, spec_decode_tokens=2, prefill_inline_budget=16
        )
        mixed = staggered_run(mixed_eng, prompts, samp)
        assert base == mixed
        st = mixed_eng.stats
        assert st.spec_proposed > 0, "speculation never engaged"
        assert st.spec_proposed == st.spec_accepted + st.spec_rejected
        assert mixed_eng.waves.snapshot()["inline_tokens"] > 0

    def test_chunk_resume_offsets_exact(self, model):
        cfg, _ = model
        samp = SamplingParams(temperature=0.0, max_new_tokens=6)
        budget = 8
        eng = make_engine(model, prefill_inline_budget=budget)
        eng.add_request(repetitive_prompt(10, 30, cfg.vocab_size), samp)
        for _ in range(3):
            eng.step()
        long_prompt = repetitive_prompt(50, 31, cfg.vocab_size)
        long_req = eng.add_request(long_prompt, samp)
        positions = []
        steps = 0
        while eng.has_work() and steps < 400:
            job = next(
                (j for j in eng._inline if j.req.rid == long_req.rid), None
            )
            if job is not None:
                positions.append(job.pos)
            eng.step()
            steps += 1
        assert positions, "the long prompt never entered the inline backlog"
        # Resume offsets: monotone, each advance at most the budget, and
        # the final chunk lands exactly at the prompt length (no token
        # skipped, none fed twice).
        for a, b in zip(positions, positions[1:]):
            assert a <= b <= a + budget
        assert long_req.kv_len >= len(long_prompt)
        assert long_req.state == RequestState.FINISHED
        assert len(long_req.output_tokens) == 6

    def test_cancel_mid_inline_releases_everything(self, model):
        cfg, _ = model
        samp = SamplingParams(temperature=0.0, max_new_tokens=8)
        eng = make_engine(model, prefill_inline_budget=8)
        carrier = eng.add_request(
            repetitive_prompt(12, 40, cfg.vocab_size), samp
        )
        for _ in range(3):
            eng.step()
        victim = eng.add_request(
            repetitive_prompt(60, 41, cfg.vocab_size), samp
        )
        eng.step()  # victim enters the backlog, advances one chunk
        assert any(j.req.rid == victim.rid for j in eng._inline)
        assert eng.cancel(victim.rid)
        assert not eng._inline
        assert not eng._inline_rows
        assert victim.cancelled
        assert victim.state == RequestState.FINISHED
        steps = 0
        while eng.has_work() and steps < 200:
            eng.step()
            steps += 1
        assert len(carrier.output_tokens) == 8
        # The freed row is admissible again.
        late = eng.add_request(
            repetitive_prompt(9, 42, cfg.vocab_size), samp
        )
        while eng.has_work():
            eng.step()
        assert len(late.output_tokens) == 8


class TestStallAttribution:
    """Satellite: the one-shot stall-cause latch. A gap spanning an
    inline chunk must attribute to the new ``prefill_inline`` cause —
    before PR 19 it fell through to ``scheduler_wait``."""

    def test_prefill_inline_in_taxonomy(self):
        assert "prefill_inline" in STALL_CAUSES

    def test_inline_gap_attributed_not_scheduler_wait(self, model):
        eng = make_engine(model, prefill_inline_budget=8)
        req = eng.make_request([1, 2, 3])
        now = time.monotonic()
        eng._last_prefill_t = now - 100.0  # no bulk prefill in the gap
        eng._last_inline_prefill_t = now - 0.01  # inline chunk inside it
        assert eng._stall_cause(req, now, gap_s=0.05) == "prefill_inline"

    def test_bulk_convoy_outranks_inline(self, model):
        eng = make_engine(model, prefill_inline_budget=8)
        req = eng.make_request([1, 2, 3])
        now = time.monotonic()
        eng._last_prefill_t = now - 0.01
        eng._last_inline_prefill_t = now - 0.01
        assert eng._stall_cause(req, now, gap_s=0.05) == "prefill_convoy"

    def test_inline_outranks_spec_miss_and_wait(self, model):
        eng = make_engine(model, prefill_inline_budget=8)
        req = eng.make_request([1, 2, 3])
        req.spec_miss = 1
        now = time.monotonic()
        eng._last_prefill_t = now - 100.0
        eng._last_inline_prefill_t = now - 0.01
        assert eng._stall_cause(req, now, gap_s=0.05) == "prefill_inline"
        # With no inline chunk in the gap the latch must NOT stick:
        # the next attribution falls through to the real cause.
        eng._last_inline_prefill_t = now - 100.0
        assert eng._stall_cause(req, now, gap_s=0.05) == "spec_verify_miss"
        assert eng._stall_cause(req, now, gap_s=0.05) == "scheduler_wait"


class TestDraftAhead:
    """Satellite: draft-ahead from the mesh. A prefix promoted by a
    PREFETCH fill or disk promotion must draft exactly like a natively
    published one — the tree's draft_ready_epoch re-arms requests whose
    tree drafting had latched off."""

    def test_promoted_prefix_yields_same_draft_as_native(self, model):
        cfg, _ = model
        eng = make_engine(model, spec_decode_tokens=4)
        prompt = repetitive_prompt(16, 50, cfg.vocab_size)
        eng.generate([prompt], SamplingParams(temperature=0.0, max_new_tokens=8))

        def mid_decode_request(prefix_len: int, tree_ok: bool):
            r = eng.make_request(prompt)
            r.kv_len = len(prompt) - 1  # history key = the full prompt
            r.prefix_len = prefix_len
            r.tree_draft_ok = tree_ok
            return r

        native = mid_decode_request(prefix_len=len(prompt), tree_ok=True)
        native_draft, native_src = eng._draft_for(native)
        assert native_src == "tree"
        assert len(native_draft) > 0

        # A remote/disk-restored request: no native prefix hit, tree
        # drafting latched off by an earlier empty peek.
        promoted = mid_decode_request(prefix_len=0, tree_ok=False)
        _, before_src = eng._draft_for(promoted)
        assert before_src != "tree"

        # The promotion lands (what kv_transfer's apply site does after
        # installing a PREFETCH/disk unit) — the epoch bump re-arms.
        eng.tree.note_draft_ready()
        promoted_draft, promoted_src = eng._draft_for(promoted)
        assert promoted_src == "tree"
        assert np.array_equal(promoted_draft, native_draft)
        assert promoted.draft_epoch == eng.tree.draft_ready_epoch

    def test_kv_transfer_apply_site_bumps_epoch(self, model):
        # The contract the draft-ahead path rides: the transfer plane's
        # apply site calls note_draft_ready (duck-typed, trees without
        # the hook are tolerated).
        import radixmesh_tpu.cache.kv_transfer as kv_transfer

        assert "note_draft_ready" in inspect.getsource(kv_transfer)
        eng = make_engine(model)
        before = eng.tree.draft_ready_epoch
        note = getattr(eng.tree, "note_draft_ready", None)
        assert note is not None
        note()
        assert eng.tree.draft_ready_epoch == before + 1


class TestStarvationVirtualTime:
    def test_decode_never_deferred_past_bound(self, model):
        # 12:1 prompt-length skew with boost waves enabled
        # (prefill_wave_tokens shrunk below the backlog). The judgment
        # is in STEP COUNTS: while inline work is pending, the carrier
        # never goes more than max_defer consecutive steps tokenless.
        cfg, _ = model
        max_defer = 1
        eng = make_engine(
            model,
            prefill_inline_budget=8,
            prefill_inline_max_defer=max_defer,
            prefill_wave_tokens=16,
        )
        carrier = eng.add_request(
            repetitive_prompt(8, 60, cfg.vocab_size),
            SamplingParams(temperature=0.0, max_new_tokens=24),
        )
        for _ in range(3):
            eng.step()
        eng.add_request(
            repetitive_prompt(96, 61, cfg.vocab_size),
            SamplingParams(temperature=0.0, max_new_tokens=4),
        )
        gap = max_gap = 0
        last = len(carrier.output_tokens)
        steps = 0
        while eng.has_work() and steps < 400:
            pending = bool(eng._inline)
            eng.step()
            steps += 1
            n = len(carrier.output_tokens)
            if n > last or not pending or n >= 24:
                gap = 0
            else:
                gap += 1
                max_gap = max(max_gap, gap)
            last = n
        snap = eng.waves.snapshot()
        assert snap["counts"]["boost"] >= 1, "skew never exercised deferral"
        assert max_gap <= max_defer
        assert snap["max_defer_observed"] <= max_defer


class TestPagedDispatch:
    def test_batch_bucket_powers_of_two(self):
        assert [batch_bucket(n) for n in (1, 2, 3, 5, 8, 9)] == [
            1, 2, 4, 8, 8, 16,
        ]
        assert batch_bucket(3, floor=8) == 8

    def test_select_paged_records_decision(self):
        # CPU backend: the kernel is unavailable, so dense always wins —
        # and the decision is recorded for /debug/state either way.
        assert select_paged(2, 128, min_batch=8, max_len=64) is False
        d = last_dispatch()
        assert d == {"path": "dense", "batch": 2, "bucket": 2, "max_len": 64}

    def test_bucketed_matches_direct_off_bucket(self):
        # B=3 pads to the 4-bucket; the padded rows must not perturb the
        # real rows' output.
        B, Hkv, D, page, per = 3, 2, 16, 4, 8
        key = jax.random.PRNGKey(0)
        k1, k2 = jax.random.split(key)
        kv = jax.random.normal(k1, (2, 1, Hkv, B * per, page, D), jnp.float32)
        q = jax.random.normal(k2, (B, Hkv, D), jnp.float32)
        pt = jnp.arange(B * per, dtype=jnp.int32).reshape(B, per)
        lens = jnp.asarray([32, 17, 5], jnp.int32)
        direct = paged_attention_pool(q, kv, pt, lens, 0, use_kernel=False)
        bucketed = paged_attention_pool_bucketed(
            q, kv, pt, lens, 0, use_kernel=False
        )
        assert bucketed.shape == direct.shape
        np.testing.assert_allclose(
            np.asarray(bucketed), np.asarray(direct), rtol=1e-5, atol=1e-5
        )

    def test_engine_exposes_dispatch_and_wave_snapshot(self, model):
        # The fields /debug/state renders: the crossover's last decision
        # and the wave-mix counters.
        cfg, _ = model
        eng = make_engine(model, prefill_inline_budget=8)
        staggered_run(
            eng,
            [
                repetitive_prompt(10, 70, cfg.vocab_size),
                repetitive_prompt(40, 71, cfg.vocab_size),
            ],
            SamplingParams(temperature=0.0, max_new_tokens=6),
        )
        assert eng._last_dispatch is not None
        assert eng._last_dispatch["path"] in ("dense", "paged")
        snap = eng.waves.snapshot()
        assert set(snap) >= {
            "budget", "max_defer", "counts", "inline_tokens",
            "decode_defer", "max_defer_observed",
        }
