"""Speculative decoding by prompt lookup (n-gram drafting + chunked
verification). The correctness bar is exactness: greedy output with
speculation ON must be bit-identical to greedy output with it OFF — every
accepted draft token is one the model would have produced anyway, and a
rejected draft's stale K/V must never leak into later steps (positions are
overwritten; attention is length-masked)."""

import numpy as np
import pytest

from radixmesh_tpu.engine import Engine, SamplingParams
from tests.test_engine import PAGE, make_engine, model, prompts_rng  # noqa: F401


class TestNgramDraft:
    def test_finds_latest_continuation(self):
        hist = np.array([1, 2, 3, 9, 1, 2, 3, 7, 5, 1, 2, 3], dtype=np.int32)
        d = Engine._ngram_draft(hist, gamma=2, n=3)
        # Tail [1,2,3] last previously occurred at 4..6, followed by 7, 5.
        assert d.tolist() == [7, 5]

    def test_bigram_fallback(self):
        hist = np.array([4, 5, 8, 0, 4, 5], dtype=np.int32)
        d = Engine._ngram_draft(hist, gamma=3, n=3)
        assert d.tolist() == [8, 0, 4]  # trigram misses, bigram [4,5] hits

    def test_no_repeat_no_draft(self):
        hist = np.arange(10, dtype=np.int32)
        assert Engine._ngram_draft(hist, gamma=4, n=3).size == 0

    def test_draft_truncated_at_history_end(self):
        hist = np.array([1, 2, 6, 1, 2], dtype=np.int32)
        d = Engine._ngram_draft(hist, gamma=4, n=2)
        assert d.tolist() == [6, 1, 2]  # continuation runs off the end


class TestSpecExactness:
    @pytest.mark.parametrize("gamma", [2, 4])
    def test_random_prompts_match_vanilla(self, model, gamma):
        cfg, params = model
        vanilla = make_engine(model)
        spec = make_engine(model, spec_decode_tokens=gamma)
        rng = prompts_rng()
        prompts = [rng.integers(1, cfg.vocab_size, n).tolist() for n in (11, 7, 15)]
        sp = SamplingParams(temperature=0.0, max_new_tokens=14)
        want = vanilla.generate(prompts, sp)
        got = spec.generate(prompts, sp)
        assert got == want

    def test_repetitive_prompt_accepts_and_matches(self, model):
        # A prompt whose tail n-grams repeat makes the drafter fire; with
        # a tiny random model most drafts still miss — exactness is the
        # invariant either way, and the drafter must have proposed.
        cfg, params = model
        vanilla = make_engine(model)
        spec = make_engine(model, spec_decode_tokens=4)
        base = prompts_rng().integers(1, cfg.vocab_size, 6).tolist()
        prompt = base * 4  # heavy n-gram repetition
        sp = SamplingParams(temperature=0.0, max_new_tokens=16)
        want = vanilla.generate([prompt], sp)
        got = spec.generate([prompt], sp)
        assert got == want
        assert spec.stats.spec_proposed > 0

    def test_cyclic_generation_gets_accepts(self, model):
        # Tiny random models typically fall into output cycles under
        # greedy decode; once the cycle enters the history the drafter
        # predicts it perfectly and acceptance must kick in. Scan a few
        # prompts for one whose vanilla output cycles, then require
        # accepted tokens AND exactness on it.
        cfg, params = model
        rng = prompts_rng()
        sp = SamplingParams(temperature=0.0, max_new_tokens=120)
        for _ in range(4):
            prompt = rng.integers(1, cfg.vocab_size, 5).tolist()
            vanilla = make_engine(model, max_seq_len=256)
            want = vanilla.generate([prompt], sp)[0]
            tail = want[-6:]
            cycles = any(tail[i:] == tail[:-i] for i in range(1, 4))
            if len(want) == 120 and cycles:
                spec = make_engine(model, max_seq_len=256, spec_decode_tokens=4)
                got = spec.generate([prompt], sp)[0]
                assert got == want
                assert spec.stats.spec_accepted > 0
                assert spec.stats.decode_steps < vanilla.stats.decode_steps
                return
        pytest.skip("no cyclic greedy output among probed prompts")

    def test_stop_token_mid_accept_matches(self, model):
        cfg, params = model
        vanilla = make_engine(model)
        ref_prompt = prompts_rng().integers(1, cfg.vocab_size, 9).tolist()
        ref = vanilla.generate(
            [ref_prompt], SamplingParams(temperature=0.0, max_new_tokens=12)
        )[0]
        stop = ref[6]
        sp = SamplingParams(
            temperature=0.0, max_new_tokens=12, stop_token_ids=(stop,)
        )
        v2 = make_engine(model)
        want = v2.generate([ref_prompt], sp)
        spec = make_engine(model, spec_decode_tokens=4)
        got = spec.generate([ref_prompt], sp)
        assert got == want

    def test_stochastic_rows_join_spec_launches(self, model):
        # Stochastic rows no longer disable speculation: a mixed batch
        # (greedy repetitive row whose drafts fire + a temperature row)
        # runs the verify launch for BOTH; the stochastic row is verified
        # by exact rejection sampling and still emits max_new_tokens
        # valid ids. (Its own drafts rarely fire with a tiny random
        # model — the sampled tail almost never repeats — so the greedy
        # row supplies the launch trigger.)
        cfg, params = model
        spec = make_engine(model, spec_decode_tokens=4)
        rng = prompts_rng()
        rep = (rng.integers(1, cfg.vocab_size, 5).tolist()) * 4
        rnd = rng.integers(1, cfg.vocab_size, 9).tolist()
        reqs = spec.add_request(rep, SamplingParams(temperature=0.0, max_new_tokens=10))
        reqs2 = spec.add_request(rnd, SamplingParams(temperature=0.9, max_new_tokens=10))
        while spec.has_work():
            spec.step()
        for r in (reqs, reqs2):
            assert len(r.output_tokens) == 10
            assert all(0 <= t < cfg.vocab_size for t in r.output_tokens)
        assert spec.stats.spec_proposed > 0

    def test_nonrepetitive_stochastic_falls_through(self, model):
        # No repeating tail → empty drafts → the cheap plain path runs.
        cfg, params = model
        spec = make_engine(model, spec_decode_tokens=4)
        prompt = prompts_rng().integers(1, cfg.vocab_size, 8).tolist()
        out = spec.generate(
            [prompt], SamplingParams(temperature=0.9, max_new_tokens=6)
        )[0]
        assert len(out) == 6
        assert spec.stats.spec_proposed == 0


    def test_cache_publish_after_spec_serves_followup(self, model):
        # Accepted-token KV written by the verify pass must be real: a
        # follow-up sharing prompt+output as its prefix should hit the
        # radix cache and still match vanilla output.
        cfg, params = model
        spec = make_engine(model, spec_decode_tokens=4)
        prompt = (prompts_rng().integers(1, cfg.vocab_size, 6).tolist()) * 3
        sp = SamplingParams(temperature=0.0, max_new_tokens=10)
        first = spec.generate([prompt], sp)[0]
        follow = prompt + first
        got = spec.generate([follow], sp)[0]
        assert spec.stats.cached_tokens > 0
        vanilla = make_engine(model)
        vanilla.generate([prompt], sp)
        want = vanilla.generate([follow], sp)[0]
        assert got == want


class TestRejectionSamplingExactness:
    def test_emitted_distribution_matches_target(self):
        """The verifier's first emitted token must be distributed exactly
        as plain sampling from the same filtered distribution, whatever
        the draft is — the core speculative-sampling identity
        P(accept d)·δ_d + P(reject)·residual = p."""
        import jax
        import jax.numpy as jnp

        from radixmesh_tpu.ops.sampling import (
            _filtered_logits,
            spec_verify_sample,
        )

        V, N = 12, 30_000
        rng = np.random.default_rng(0)
        logits_row = jnp.asarray(rng.normal(size=(V,)) * 2.0, jnp.float32)
        temperature, top_p = 0.8, 0.85
        # Target distribution: exactly what sample_tokens would draw from.
        filt = _filtered_logits(
            logits_row[None, :],
            jnp.asarray([temperature]),
            jnp.asarray([top_p]),
        )
        target = np.asarray(jax.nn.softmax(filt, axis=-1))[0]

        # Batch N independent verifications of a 1-token draft (both an
        # in-nucleus and an out-of-nucleus draft token).
        for draft_tok in (int(np.argmax(target)), int(np.argmin(target))):
            logits = jnp.broadcast_to(logits_row, (N, 2, V))
            drafts = jnp.full((N, 1), draft_tok, jnp.int32)
            dlen = jnp.ones((N,), jnp.int32)
            accept_len, bonus = spec_verify_sample(
                logits, drafts, dlen, jax.random.PRNGKey(7),
                jnp.full((N,), temperature), jnp.full((N,), top_p),
            )
            accept_len = np.asarray(accept_len)
            bonus = np.asarray(bonus)
            emitted = np.where(accept_len > 0, draft_tok, bonus)
            freq = np.bincount(emitted, minlength=V) / N
            # TV distance well under sampling noise for N=30k.
            tv = 0.5 * np.abs(freq - target).sum()
            assert tv < 0.02, (draft_tok, tv, freq, target)



class TestTreeDrafts:
    """The radix cache doubles as the drafter: a replayed request finds
    the previous generation's published tokens cached beyond its history
    and accepts them wholesale under greedy decode."""

    def test_peek_continuation_basics(self, model):
        from radixmesh_tpu.cache.radix_tree import RadixTree

        tree = RadixTree(page_size=1)
        tree.insert([1, 2, 3, 4, 5, 6], np.arange(6, dtype=np.int32))
        assert tree.peek_continuation([1, 2, 3], 2).tolist() == [4, 5]
        assert tree.peek_continuation([1, 2, 3], 10).tolist() == [4, 5, 6]
        assert tree.peek_continuation([1, 9], 4).size == 0  # diverged
        assert tree.peek_continuation([1, 2, 3, 4, 5, 6], 4).size == 0  # exhausted

    def test_replay_accepts_heavily_and_matches(self, model):
        cfg, params = model
        vanilla = make_engine(model)
        spec = make_engine(model, spec_decode_tokens=4)
        prompt = prompts_rng().integers(1, cfg.vocab_size, 13).tolist()
        sp = SamplingParams(temperature=0.0, max_new_tokens=16)
        want = vanilla.generate([prompt], sp)
        first = spec.generate([prompt], sp)
        assert first == want
        steps_first = spec.stats.decode_steps
        # Replay: the tree now holds the full previous sequence, so the
        # drafter proposes the real continuation every launch.
        second = spec.generate([prompt], sp)
        assert second == want
        assert spec.stats.spec_accepted >= 8, spec.stats
        assert (spec.stats.decode_steps - steps_first) < steps_first, (
            "replay did not speed up",
            spec.stats.decode_steps,
            steps_first,
        )
