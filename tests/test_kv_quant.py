"""Int8 KV-cache quantization: pool storage, attention numerics (oracle +
Pallas kernels in interpreter mode), and engine integration.

Decode streams the whole context's K/V per layer per token, so int8 pages
halve the dominant HBM traffic (SURVEY §6). Correctness bar: quantized
attention must match the *quantized oracle* almost exactly (same int8
values, same scales — the only difference is contraction order), and the
end-to-end engine must stay functional with bounded numeric drift.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from radixmesh_tpu.cache.kv_pool import PagedKVPool
from radixmesh_tpu.engine import Engine, SamplingParams
from radixmesh_tpu.models.llama import ModelConfig, init_params
from radixmesh_tpu.ops.attention import attend_decode_ref
from radixmesh_tpu.ops.paged_attention import (
    paged_attention_pool_kernel,
    paged_decode_fused_kernel,
)
from radixmesh_tpu.ops.quant import dequantize_kv, quantize_kv


class TestQuantHelpers:
    def test_round_trip_error_bound(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(4, 64, 128)) * 3.0, jnp.float32)
        q, s = quantize_kv(x, axis=-1)
        back = dequantize_kv(q, s, axis=-1)
        # Symmetric int8: |err| <= scale/2 = amax/254 per vector.
        amax = np.max(np.abs(np.asarray(x)), axis=-1, keepdims=True)
        assert np.all(np.abs(np.asarray(back) - np.asarray(x)) <= amax / 253)

    def test_zero_vector_safe(self):
        q, s = quantize_kv(jnp.zeros((3, 8)), axis=-1)
        assert np.all(np.asarray(q) == 0) and np.all(np.asarray(s) > 0)
        assert np.all(np.asarray(dequantize_kv(q, s)) == 0)


class TestQuantPool:
    def test_write_gather_round_trip(self):
        rng = np.random.default_rng(1)
        pool = PagedKVPool(
            num_slots=64, num_layers=2, num_kv_heads=2, head_dim=16,
            page_size=4, quant="int8",
        )
        assert pool.kv.dtype == jnp.int8
        slots = pool.alloc(10)
        k = jnp.asarray(rng.normal(size=(2, 10, 2, 16)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(2, 10, 2, 16)), jnp.float32)
        pool.write(slots, k, v)
        g = np.asarray(pool.gather(slots))  # dequantized [2, L, n, H, D]
        for src, got in ((k, g[0]), (v, g[1])):
            src = np.asarray(src).transpose(0, 1, 2, 3)
            amax = np.max(np.abs(src), axis=-1, keepdims=True)
            assert np.all(np.abs(got - src) <= amax / 250 + 1e-7)

    def test_rejects_unknown_quant(self):
        with pytest.raises(ValueError):
            PagedKVPool(num_slots=8, num_layers=1, num_kv_heads=1, head_dim=8,
                        quant="fp4")


def _quantized_pool_fixture(rng, L=2, Hkv=4, D=128, page=16, P=32):
    kv = jnp.asarray(rng.normal(size=(2, L, Hkv, P * page, D)), jnp.float32)
    q8, sc = quantize_kv(kv, axis=-1)
    return (
        q8.reshape(2, L, Hkv, P, page, D),
        sc.reshape(2, L, Hkv, P, page),
    )


class TestQuantKernels:
    def test_pool_kernel_matches_quant_oracle(self):
        rng = np.random.default_rng(2)
        kvp, scp = _quantized_pool_fixture(rng)
        B, Hq, D, page, P, maxp = 3, 8, 128, 16, 32, 8
        q = jnp.asarray(rng.normal(size=(B, Hq, D)), jnp.float32)
        pt = jnp.asarray(rng.permutation(P)[: B * maxp].reshape(B, maxp), jnp.int32)
        ln = jnp.asarray([1, 3 * page + 5, maxp * page], jnp.int32)
        for layer in (0, 1):
            want = np.asarray(
                attend_decode_ref(
                    q, kvp[0, layer], kvp[1, layer], pt, ln,
                    scp[0, layer], scp[1, layer],
                ),
                np.float32,
            )
            got = np.asarray(
                paged_attention_pool_kernel(
                    q, kvp, pt, ln, layer, interpret=True, kv_scales=scp
                ),
                np.float32,
            )
            np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_fused_kernel_writes_and_matches(self):
        rng = np.random.default_rng(3)
        kvp, scp = _quantized_pool_fixture(rng)
        B, Hq, Hkv, D, page, P, maxp = 3, 8, 4, 128, 16, 32, 8
        layer = 1
        q = jnp.asarray(rng.normal(size=(B, Hq, D)), jnp.float32)
        k_new = jnp.asarray(rng.normal(size=(B, Hkv, D)), jnp.float32)
        v_new = jnp.asarray(rng.normal(size=(B, Hkv, D)), jnp.float32)
        pt = jnp.asarray(rng.permutation(P)[: B * maxp].reshape(B, maxp), jnp.int32)
        ln = jnp.asarray([1, 3 * page + 6, maxp * page], jnp.int32)
        slots = jnp.asarray(
            [
                int(pt[b, (int(ln[b]) - 1) // page]) * page
                + (int(ln[b]) - 1) % page
                for b in range(B)
            ],
            jnp.int32,
        )
        out, kv2, sc2 = paged_decode_fused_kernel(
            q, k_new, v_new, kvp, slots, pt, ln, layer,
            interpret=True, kv_scales=scp,
        )
        # Oracle: quantize the row identically, scatter, attend with scales.
        kq, ksc = quantize_kv(k_new, axis=-1)
        vq, vsc = quantize_kv(v_new, axis=-1)
        S = P * page
        kvp_o = kvp.at[0, layer].set(
            kvp[0, layer].reshape(Hkv, S, D).at[:, slots]
            .set(kq.transpose(1, 0, 2)).reshape(Hkv, P, page, D)
        )
        kvp_o = kvp_o.at[1, layer].set(
            kvp[1, layer].reshape(Hkv, S, D).at[:, slots]
            .set(vq.transpose(1, 0, 2)).reshape(Hkv, P, page, D)
        )
        scp_o = scp.at[0, layer].set(
            scp[0, layer].reshape(Hkv, S).at[:, slots].set(ksc.T)
            .reshape(Hkv, P, page)
        )
        scp_o = scp_o.at[1, layer].set(
            scp[1, layer].reshape(Hkv, S).at[:, slots].set(vsc.T)
            .reshape(Hkv, P, page)
        )
        want = np.asarray(
            attend_decode_ref(
                q, kvp_o[0, layer], kvp_o[1, layer], pt, ln,
                scp_o[0, layer], scp_o[1, layer],
            ),
            np.float32,
        )
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4, atol=1e-5)
        # Pool updates are bit-exact vs the reference quantizer.
        assert np.array_equal(np.asarray(kv2), np.asarray(kvp_o))
        np.testing.assert_allclose(np.asarray(sc2), np.asarray(scp_o), rtol=1e-6)


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny().replace(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(7))
    return cfg, params


def quant_engine(model, **kw):
    cfg, params = model
    kw.setdefault("num_slots", 512)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq_len", 128)
    return Engine(cfg, params, kv_quant="int8", **kw)


class TestQuantEngine:
    def test_generates_and_first_token_exact(self, model):
        cfg, params = model
        rng = np.random.default_rng(5)
        prompts = [rng.integers(1, cfg.vocab_size, n).tolist() for n in (9, 13)]
        sp = SamplingParams(temperature=0.0, max_new_tokens=8)
        ref = Engine(cfg, params, num_slots=512, page_size=4, max_batch=4,
                     max_seq_len=128).generate(prompts, sp)
        eng = quant_engine(model)
        out = eng.generate(prompts, sp)
        assert all(len(o) == 8 for o in out)
        # Quantized engines prefill through the chunked paged path, which
        # attends the quantize→dequantized K/V — logits drift from bf16 is
        # bounded by the int8 step (~amax/254 per value), far below this
        # model's argmax margins, so the first sampled token agrees.
        for o, r in zip(out, ref):
            assert o[0] == r[0]

    def test_prefix_cache_hit_serves_from_quant_pool(self, model):
        cfg, params = model
        rng = np.random.default_rng(6)
        eng = quant_engine(model)
        prompt = rng.integers(1, cfg.vocab_size, 12).tolist()
        sp = SamplingParams(temperature=0.0, max_new_tokens=6)
        first = eng.generate([prompt], sp)[0]
        follow = prompt + first
        out = eng.generate([follow], sp)[0]
        assert eng.stats.cached_tokens > 0
        assert len(out) == 6

    def test_chunked_long_prefill_quant(self, model):
        cfg, params = model
        rng = np.random.default_rng(7)
        eng = quant_engine(model, long_prefill_threshold=16, prefill_chunk=16,
                           num_slots=1024, max_seq_len=256)
        prompt = rng.integers(1, cfg.vocab_size, 90).tolist()
        out = eng.generate(
            [prompt], SamplingParams(temperature=0.0, max_new_tokens=5)
        )[0]
        assert len(out) == 5

    def test_multi_step_and_spec_paths_quant(self, model):
        cfg, params = model
        rng = np.random.default_rng(8)
        prompt = rng.integers(1, cfg.vocab_size, 10).tolist()
        sp = SamplingParams(temperature=0.0, max_new_tokens=9)
        ref = quant_engine(model).generate([prompt], sp)[0]
        multi = quant_engine(model, decode_steps_per_launch=3)
        assert multi.generate([prompt], sp)[0] == ref
        spec = quant_engine(model, spec_decode_tokens=3)
        assert spec.generate([prompt], sp)[0] == ref

    def test_quant_engine_with_host_tier(self, model):
        # Int8 pool + host-RAM tier: evicted prefixes back up as int8 +
        # scales and restore verbatim; a follow-up still serves correctly.
        cfg, params = model
        eng = quant_engine(model, num_slots=64, host_cache_slots=256)
        rng = np.random.default_rng(12)
        sp = SamplingParams(temperature=0.0, max_new_tokens=4)
        prompts = [rng.integers(1, cfg.vocab_size, 14).tolist() for _ in range(4)]
        for p in prompts:  # churn a tiny pool to force write-backs
            eng.generate([p], sp)
        out = eng.generate([prompts[0]], sp)[0]
        assert len(out) == 4

    def test_sharded_quant_engine_matches_single_device(self, model):
        """tp-sharded serving over a quantized pool: same greedy tokens as
        the unsharded quantized engine (sharding must not change decode
        math; scales shard with their kv heads)."""
        cfg, params = model
        from radixmesh_tpu.parallel.sharding import MeshPlan, make_mesh

        mesh = make_mesh(MeshPlan(dp=1, sp=1, tp=2))
        rng = np.random.default_rng(9)
        prompts = [rng.integers(1, cfg.vocab_size, n).tolist() for n in (8, 11)]
        sp = SamplingParams(temperature=0.0, max_new_tokens=7)
        want = quant_engine(model).generate(prompts, sp)
        got = quant_engine(model, device_mesh=mesh).generate(prompts, sp)
        assert got == want

    def test_sharded_quant_kernel_matches_oracle(self):
        """The shard_map'd quantized pool kernel (interpret mode on the
        CPU mesh) against the quantized jnp oracle."""
        from radixmesh_tpu.ops.attention import (
            paged_attention_pool_kernel_sharded,
        )
        from radixmesh_tpu.parallel.sharding import MeshPlan, make_mesh

        mesh = make_mesh(MeshPlan(dp=1, sp=1, tp=2))
        rng = np.random.default_rng(10)
        kvp, scp = _quantized_pool_fixture(rng, L=2, Hkv=4, D=128, page=16, P=16)
        B, Hq, D, page, P, maxp = 2, 8, 128, 16, 16, 4
        q = jnp.asarray(rng.normal(size=(B, Hq, D)), jnp.float32)
        pt = jnp.asarray(
            rng.permutation(P)[: B * maxp].reshape(B, maxp), jnp.int32
        )
        ln = jnp.asarray([page + 2, maxp * page], jnp.int32)
        want = np.asarray(
            attend_decode_ref(
                q, kvp[0, 1], kvp[1, 1], pt, ln, scp[0, 1], scp[1, 1]
            ),
            np.float32,
        )
        got = np.asarray(
            paged_attention_pool_kernel_sharded(
                q, kvp, pt, ln, 1, mesh, interpret=True, kv_scales=scp
            ),
            np.float32,
        )
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
