"""Multi-host compute plane: ``jax.distributed`` across OS processes.

The reference's multi-node story is the oplog ring; SURVEY §5 requires the
rebuild's COMPUTE to scale multi-host too (the role NCCL/MPI plays in
torch stacks). This runs the real thing on CPU: two processes join one
``jax.distributed`` job (Gloo collectives), form ONE global mesh over
their 4+4 virtual devices, and execute the same sharded train step the
single-host dryrun runs — cross-process collectives and all. Loss must be
finite and identical on every process AND equal to the single-process
8-device result (the mesh factorization is the same, so the math is)."""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(pid: int, nproc: int, port: int) -> subprocess.Popen:
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        # The per-process flag is set by init_multihost via --local-devices;
        # scrub the suite's 8-device conftest flag so it doesn't override.
        XLA_FLAGS="",
    )
    return subprocess.Popen(
        [
            sys.executable, "-m", "radixmesh_tpu.launch", "multihost-dryrun",
            "--coordinator", f"127.0.0.1:{port}",
            "--num-processes", str(nproc),
            "--process-id", str(pid),
            "--local-devices", "4",
            # Pin the mesh the single-process 8-device dryrun uses so the
            # pinned loss proves cross-process == single-host math (the
            # host-aligned DEFAULT plan would pick dp=2,sp=1,tp=4).
            "--mesh", "1,2,4",
        ],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )


def test_two_process_global_mesh_train_step():
    port = _free_port()
    procs = [_spawn(i, 2, port) for i in range(2)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost dryrun hung")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} rc={p.returncode}:\n{out[-2000:]}"

    losses = []
    for out in outs:
        m = re.search(
            r"devices 4 local / 8 global mesh=(\{[^}]*\}) loss=([\d.]+)", out
        )
        assert m, f"missing dryrun line in:\n{out[-2000:]}"
        losses.append(float(m.group(2)))
    assert losses[0] == losses[1], losses
    # Same mesh factorization as the single-process 8-device dryrun →
    # identical math; the known-good loss pins cross-process collectives
    # to the single-host result.
    assert abs(losses[0] - 6.7823) < 5e-3, losses
