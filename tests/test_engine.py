"""Serving-engine tests: continuous batching + prefix reuse against a
full-recompute oracle (tiny fp32 model on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from radixmesh_tpu.engine import Engine, RequestState, SamplingParams
from radixmesh_tpu.models.llama import ModelConfig, init_params, prefill_forward

pytestmark = pytest.mark.quick

PAGE = 4


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny().replace(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(7))
    return cfg, params


def make_engine(model, **kw):
    cfg, params = model
    kw.setdefault("num_slots", 512)
    kw.setdefault("page_size", PAGE)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq_len", 128)
    return Engine(cfg, params, **kw)


def oracle_generate(cfg, params, prompt, n_new):
    """Greedy decode by full dense recompute each step — no cache, no pool."""
    toks = list(int(t) for t in prompt)
    for _ in range(n_new):
        s = len(toks)
        s_b = max(8, 1 << (s - 1).bit_length())
        tokens = np.zeros((1, s_b), dtype=np.int32)
        tokens[0, :s] = toks
        positions = np.arange(s_b, dtype=np.int32)[None]
        ck = jnp.zeros((cfg.n_layers, 1, 0, cfg.n_kv_heads, cfg.head_dim), cfg.dtype)
        logits, _, _ = prefill_forward(
            params, cfg, jnp.asarray(tokens), jnp.asarray(positions),
            ck, ck, jnp.zeros((1,), jnp.int32),
        )
        toks.append(int(jnp.argmax(logits[0, s - 1])))
    return toks[len(prompt) :]


def prompts_rng():
    return np.random.default_rng(3)


class TestGenerate:
    def test_matches_oracle_single(self, model):
        cfg, params = model
        prompt = prompts_rng().integers(0, cfg.vocab_size, 13).tolist()
        eng = make_engine(model)
        out = eng.generate([prompt], SamplingParams(max_new_tokens=9))[0]
        assert out == oracle_generate(cfg, params, prompt, 9)

    def test_batch_matches_sequential(self, model):
        cfg, params = model
        rng = prompts_rng()
        prompts = [rng.integers(0, cfg.vocab_size, n).tolist() for n in (5, 12, 21)]
        eng = make_engine(model)
        outs = eng.generate(prompts, SamplingParams(max_new_tokens=7))
        for p, o in zip(prompts, outs):
            assert o == oracle_generate(cfg, params, p, 7)

    def test_stop_token(self, model):
        cfg, params = model
        prompt = prompts_rng().integers(0, cfg.vocab_size, 10).tolist()
        ref = oracle_generate(cfg, params, prompt, 8)
        stop = ref[3]
        eng = make_engine(model)
        out = eng.generate(
            [prompt], SamplingParams(max_new_tokens=8, stop_token_ids=(stop,))
        )[0]
        assert out == ref[:3]

    def test_more_requests_than_rows(self, model):
        cfg, params = model
        rng = prompts_rng()
        prompts = [rng.integers(0, cfg.vocab_size, 6 + i).tolist() for i in range(7)]
        eng = make_engine(model, max_batch=2)
        outs = eng.generate(prompts, SamplingParams(max_new_tokens=5))
        for p, o in zip(prompts, outs):
            assert o == oracle_generate(cfg, params, p, 5)


class TestPrefixReuse:
    def test_second_request_hits_cache(self, model):
        cfg, params = model
        prompt = prompts_rng().integers(0, cfg.vocab_size, 24).tolist()
        eng = make_engine(model)
        out1 = eng.generate([prompt], SamplingParams(max_new_tokens=6))[0]
        assert eng.stats.cached_tokens == 0
        out2 = eng.generate([prompt], SamplingParams(max_new_tokens=6))[0]
        assert out1 == out2
        # ≥ the page-aligned prompt minus the one-token prefill floor
        assert eng.stats.cached_tokens >= (len(prompt) - 1) // PAGE * PAGE
        assert eng.stats.hit_rate > 0.4

    def test_shared_prefix_across_requests(self, model):
        cfg, params = model
        rng = prompts_rng()
        common = rng.integers(0, cfg.vocab_size, 16).tolist()
        p1 = common + rng.integers(0, cfg.vocab_size, 4).tolist()
        p2 = common + rng.integers(0, cfg.vocab_size, 5).tolist()
        eng = make_engine(model)
        o1, o2 = eng.generate([p1, p2], SamplingParams(max_new_tokens=4))
        assert o1 == oracle_generate(cfg, params, p1, 4)
        assert o2 == oracle_generate(cfg, params, p2, 4)

    def test_generated_tokens_are_reusable(self, model):
        cfg, params = model
        prompt = prompts_rng().integers(0, cfg.vocab_size, 8).tolist()
        eng = make_engine(model)
        out = eng.generate([prompt], SamplingParams(max_new_tokens=10))[0]
        # A prompt extending into the generated text should hit the cache
        # beyond the original prompt (cache_finished_req published it).
        longer = prompt + out[:6]
        eng.generate([longer], SamplingParams(max_new_tokens=2))
        assert eng.stats.cached_tokens >= (len(longer) - 1) // PAGE * PAGE


class TestMemoryPressure:
    def test_eviction_keeps_engine_alive(self, model):
        cfg, params = model
        rng = prompts_rng()
        # Pool of 96 slots; each request needs ~24 — the 10 requests only
        # fit because finished trees get evicted under pressure.
        eng = make_engine(model, num_slots=96, max_batch=2)
        prompts = [rng.integers(0, cfg.vocab_size, 16).tolist() for _ in range(10)]
        outs = eng.generate(prompts, SamplingParams(max_new_tokens=6))
        for p, o in zip(prompts, outs):
            assert o == oracle_generate(cfg, params, p, 6)

    def test_all_slots_recovered_after_reset(self, model):
        eng = make_engine(model)
        rng = prompts_rng()
        prompts = [rng.integers(0, eng.cfg.vocab_size, 12).tolist() for _ in range(3)]
        eng.generate(prompts, SamplingParams(max_new_tokens=4))
        eng.tree.reset()
        # everything except the scratch page is back
        assert eng.pool.free_slots == eng.pool.num_slots - PAGE


class TestSamplingIntegration:
    def test_temperature_sampling_runs(self, model):
        eng = make_engine(model)
        prompt = prompts_rng().integers(0, eng.cfg.vocab_size, 9).tolist()
        out = eng.generate(
            [prompt], SamplingParams(max_new_tokens=5, temperature=0.8, top_p=0.9)
        )[0]
        assert len(out) == 5
        assert all(0 <= t < eng.cfg.vocab_size for t in out)


class TestMultiStepDecode:
    """decode_steps_per_launch > 1: k tokens per launch with device-side
    sampling — greedy output must be IDENTICAL to step-at-a-time decode
    (same decode math, same argmax), stops truncate mid-launch, and page
    boundaries are provisioned ahead."""

    def _engines(self, model, k, **kw):
        cfg, params = model
        single = make_engine(model, **kw)
        multi = make_engine(model, **kw)
        multi.decode_steps_per_launch = k
        return single, multi

    @pytest.mark.parametrize("k", [2, 4, 7])
    def test_greedy_matches_single_step(self, model, k):
        cfg, params = model
        single, multi = self._engines(model, k)
        prompts = [
            prompts_rng().integers(1, cfg.vocab_size, n).tolist()
            for n in (9, 14)
        ]
        sp = SamplingParams(temperature=0.0, max_new_tokens=13)
        want = single.generate(prompts, sp)
        got = multi.generate(prompts, sp)
        assert got == want
        assert multi.stats.generated_tokens == single.stats.generated_tokens

    def test_stop_token_truncates_mid_launch(self, model):
        cfg, params = model
        single, multi = self._engines(model, 4)
        prompt = prompts_rng().integers(1, cfg.vocab_size, 10).tolist()
        ref = single.generate(
            [prompt], SamplingParams(temperature=0.0, max_new_tokens=12)
        )[0]
        stop = ref[5]  # force a stop mid-way (and mid-k-batch)
        sp = SamplingParams(
            temperature=0.0, max_new_tokens=12, stop_token_ids=(stop,)
        )
        got = multi.generate([prompt], sp)[0]
        want_len = ref.index(stop)
        assert got == ref[:want_len]

    def test_row_stops_mid_launch_while_others_continue(self, model):
        # The risky interaction in the fused path: _consume_token releases
        # row A mid-launch (page table reset, row reassignable) while the
        # host loop keeps consuming the SAME launch's sampled tokens for
        # rows B..N — their output must be unaffected by A's release.
        cfg, params = model
        single, multi = self._engines(model, 4)
        rng = prompts_rng()
        prompts = [rng.integers(1, cfg.vocab_size, n).tolist() for n in (10, 8, 13)]
        sp0 = SamplingParams(temperature=0.0, max_new_tokens=12)
        refs = single.generate(prompts, sp0)
        # Stop token chosen so prompt 0 halts mid-k-batch; with greedy
        # decode the other rows' streams are unchanged unless they also
        # emit it (then they truncate identically — still equal to ref).
        stop = refs[0][5]
        sp = SamplingParams(
            temperature=0.0, max_new_tokens=12, stop_token_ids=(stop,)
        )
        got = multi.generate(prompts, sp)
        for out, ref in zip(got, refs):
            want = ref[: ref.index(stop)] if stop in ref else ref
            assert out == want

    def test_crosses_pages_and_reuses_cache(self, model):
        cfg, params = model
        single, multi = self._engines(model, 5)
        prompt = prompts_rng().integers(1, cfg.vocab_size, 7).tolist()
        sp = SamplingParams(temperature=0.0, max_new_tokens=17)  # > 4 pages
        want = single.generate([prompt], sp)[0]
        got = multi.generate([prompt], sp)[0]
        assert got == want
        # Published sequence serves a follow-up from cache.
        follow = prompt + got[:10]
        multi.generate([follow], SamplingParams(temperature=0.0, max_new_tokens=2))
        assert multi.stats.cached_tokens >= (len(follow) - 1) // PAGE * PAGE


class TestCancel:
    def test_cancel_queued(self, model):
        cfg, params = model
        eng = make_engine(model, max_batch=1)
        r1 = eng.add_request([1, 2, 3], SamplingParams(max_new_tokens=4))
        r2 = eng.add_request([4, 5, 6], SamplingParams(max_new_tokens=4))
        assert eng.cancel(r2.rid)
        while eng.has_work():
            eng.step()
        assert len(r1.output_tokens) == 4
        assert r2.cancelled and r2.output_tokens == []
        assert not eng.cancel(r2.rid)  # already finished

    def test_cancel_running_releases_row_and_publishes(self, model):
        cfg, params = model
        eng = make_engine(model, max_batch=1)
        prompt = prompts_rng().integers(1, cfg.vocab_size, 10).tolist()
        req = eng.add_request(prompt, SamplingParams(max_new_tokens=64))
        for _ in range(6):  # prefill + a few decode steps
            eng.step()
        produced = len(req.output_tokens)
        assert 0 < produced < 64
        assert eng.cancel(req.rid)
        assert req.cancelled and len(req.output_tokens) == produced
        # The row is free for new work and the computed prefix is cached.
        follow = eng.generate(
            [prompt + req.output_tokens],
            SamplingParams(temperature=0.0, max_new_tokens=2),
        )[0]
        assert len(follow) == 2
        assert eng.stats.cached_tokens > 0

    def test_cancel_unknown_rid(self, model):
        eng = make_engine(model)
        assert not eng.cancel(10_000)


class TestTopK:
    def test_top_k_one_is_greedy(self, model):
        cfg, params = model
        prompt = prompts_rng().integers(1, cfg.vocab_size, 9).tolist()
        ref = make_engine(model).generate(
            [prompt], SamplingParams(temperature=0.0, max_new_tokens=8)
        )[0]
        # k=1 restricts sampling to the argmax even at high temperature.
        out = make_engine(model).generate(
            [prompt],
            SamplingParams(temperature=1.5, top_k=1, max_new_tokens=8),
        )[0]
        assert out == ref

    def test_per_row_top_k_mixed_batch(self, model):
        cfg, params = model
        rng = prompts_rng()
        prompts = [rng.integers(1, cfg.vocab_size, n).tolist() for n in (7, 9)]
        eng = make_engine(model)
        r1 = eng.add_request(
            prompts[0], SamplingParams(temperature=1.2, top_k=1, max_new_tokens=6)
        )
        r2 = eng.add_request(
            prompts[1], SamplingParams(temperature=0.8, max_new_tokens=6)
        )
        while eng.has_work():
            eng.step()
        ref = make_engine(model).generate(
            [prompts[0]], SamplingParams(temperature=0.0, max_new_tokens=6)
        )[0]
        assert r1.output_tokens == ref  # k=1 row is effectively greedy
        assert len(r2.output_tokens) == 6

    def test_top_k_with_multi_step_and_spec(self, model):
        cfg, params = model
        prompt = (prompts_rng().integers(1, cfg.vocab_size, 5).tolist()) * 3
        sp = SamplingParams(temperature=1.0, top_k=1, max_new_tokens=9)
        ref = make_engine(model).generate([prompt], sp)[0]
        multi = make_engine(model, decode_steps_per_launch=3)
        assert multi.generate([prompt], sp)[0] == ref
        spec = make_engine(model, spec_decode_tokens=3)
        assert spec.generate([prompt], sp)[0] == ref


class TestSeqLenBoundary:
    @pytest.mark.parametrize("kw", [
        {},
        {"spec_decode_tokens": 3},
        {"decode_steps_per_launch": 3},
    ])
    def test_generation_truncates_at_max_seq_len(self, model, kw):
        """A budget larger than the remaining context must truncate at
        max_seq_len on every decode path (plain, speculative, fused) —
        spec/fused decline near the cap and the plain path finishes."""
        cfg = model[0]
        eng = make_engine(model, max_seq_len=32, max_batch=1, **kw)
        prompt = prompts_rng().integers(1, cfg.vocab_size, 25).tolist()
        out = eng.generate([prompt], SamplingParams(max_new_tokens=64))[0]
        assert len(prompt) + len(out) == 32
        # The (only) row is genuinely released and the engine still serves.
        assert all(r is None for r in eng._rows)
        out2 = eng.generate([[1, 2, 3]], SamplingParams(max_new_tokens=2))[0]
        assert len(out2) == 2


class TestPrefillWaveSlicing:
    """Round-5 cold-burst fairness (VERDICT r4 weak #4): a burst of equal
    cold requests must prefill in arrival-ordered slices of at most
    ``prefill_wave_tokens // chunk`` rows — each slice finalizing its own
    first tokens — instead of one convoy whose every member waits for the
    last. Output correctness is pinned against the unsliced engine."""

    def test_burst_slices_and_matches_unsliced(self, model):
        cfg, params = model
        rng = prompts_rng()
        prompts = [rng.integers(0, cfg.vocab_size, 24).tolist() for _ in range(6)]
        # Distinct first tokens so no prefix-wave deferral kicks in.
        for i, p in enumerate(prompts):
            p[0] = i + 1

        # prefill_wave_tokens=64 with 24-token cold prompts (bucket 32)
        # → slices of 2 rows.
        eng = make_engine(
            model, max_batch=6, prefill_wave_tokens=64,
            long_prefill_threshold=0,  # force the grouped paged path
        )
        waves: list[int] = []
        orig = eng._prefill_group

        def spy(group):
            waves.append(len(group))
            return orig(group)

        eng._prefill_group = spy
        out = eng.generate(prompts, SamplingParams(max_new_tokens=4))

        assert waves and max(waves) <= 2, waves
        assert sum(waves) == 6

        eng_wide = make_engine(
            model, max_batch=6, prefill_wave_tokens=1 << 20,
            long_prefill_threshold=0,
        )
        want = eng_wide.generate(prompts, SamplingParams(max_new_tokens=4))
        assert out == want
