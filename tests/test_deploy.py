"""Deployment artifacts (docker/ + deploy/configs) stay consistent.

The reference's image is broken at build time (copies a nonexistent
requirements.txt, ``docker/Dockerfile:32``) — these checks keep ours from
rotting the same way: every node config must load through the real
``load_config`` validator, describe ONE identical topology, and agree
with the compose file's service set; everything the Dockerfile COPYs must
exist.
"""

import pathlib
import re

import yaml

from radixmesh_tpu.config import NodeRole, load_config

ROOT = pathlib.Path(__file__).resolve().parent.parent
CONFIGS = sorted((ROOT / "deploy" / "configs").glob("*.yaml"))


def test_six_node_topology_loads_and_is_consistent():
    assert len(CONFIGS) == 6
    cfgs = [load_config(str(p)) for p in CONFIGS]
    topo = {
        (tuple(c.prefill_nodes), tuple(c.decode_nodes), tuple(c.router_nodes))
        for c in cfgs
    }
    assert len(topo) == 1, "configs must be identical except local_addr"
    roles = [c.local_identity()[0] for c in cfgs]
    assert roles.count(NodeRole.PREFILL) == 3
    assert roles.count(NodeRole.DECODE) == 2
    assert roles.count(NodeRole.ROUTER) == 1
    # Every cluster member has exactly one config file.
    addrs = {c.local_addr for c in cfgs}
    (p, d, r) = next(iter(topo))
    assert addrs == set(p) | set(d) | set(r)


def test_serving_nodes_have_model_sections():
    for path in CONFIGS:
        cfg = load_config(str(path))
        role = cfg.local_identity()[0]
        if role is NodeRole.ROUTER:
            assert not cfg.model, "router must not load a model"
        else:
            assert cfg.model, f"{path.name}: serving node needs a model section"
            assert cfg.model.get("preset")


def test_compose_services_match_configs():
    compose = yaml.safe_load((ROOT / "docker" / "compose.yaml").read_text())
    services = set(compose["services"])
    assert services == {p.stem for p in CONFIGS}
    for name, svc in compose["services"].items():
        cmd = svc["command"]
        assert cmd[0] == "node"
        assert f"/configs/{name}.yaml" in cmd


def test_dockerfile_copies_exist():
    text = (ROOT / "docker" / "Dockerfile").read_text()
    for m in re.finditer(r"^COPY\s+(.+?)\s+\S+$", text, re.M):
        for src in m.group(1).split():
            assert (ROOT / src).exists(), f"Dockerfile COPYs missing {src}"
