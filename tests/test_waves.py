"""Wave-scheduler invariants (PR 19, mixed compute waves): the pure
host-side policy in engine/waves.py, provable without a device —
the budget is never exceeded, decode is never deferred past
``--prefill-inline-max-defer`` consecutive waves, allotment is
shortest-remaining-first with FIFO tiebreak, and the accounting the
/debug/state snapshot reads stays consistent."""

import numpy as np
import pytest

from radixmesh_tpu.engine.waves import WAVE_KINDS, WavePlan, WaveScheduler

pytestmark = pytest.mark.quick


def make(budget=32, max_defer=2, chunk=512, boost=128):
    return WaveScheduler(
        inline_budget=budget, max_defer=max_defer, chunk=chunk,
        boost_tokens=boost,
    )


class TestBudgetInvariant:
    def test_mixed_wave_never_exceeds_budget(self):
        rng = np.random.default_rng(0)
        ws = make(budget=32, boost=10_000)  # boost unreachable
        for _ in range(200):
            backlog = rng.integers(0, 400, size=rng.integers(1, 6)).tolist()
            plan = ws.plan(decode_rows=2, backlog=backlog)
            assert plan.kind in WAVE_KINDS
            assert sum(plan.allot) <= ws.inline_budget
            for a, r in zip(plan.allot, backlog):
                assert 0 <= a <= min(r, ws.chunk)

    def test_boost_wave_bounded_by_boost_tokens(self):
        ws = make(budget=32, boost=128)
        plan = ws.plan(decode_rows=1, backlog=[500, 500])
        assert plan.kind == "boost"
        assert not plan.decode
        assert sum(plan.allot) <= ws.boost_tokens

    def test_chunk_caps_single_job_share(self):
        ws = make(budget=4096, chunk=512, boost=100_000)
        plan = ws.plan(decode_rows=1, backlog=[10_000])
        assert plan.allot == [512]

    def test_zero_budget_rejected(self):
        with pytest.raises(ValueError):
            WaveScheduler(inline_budget=0)


class TestStarvationBound:
    def test_defer_never_exceeds_bound_under_adversarial_backlog(self):
        # Backlog always deep enough to justify a boost: the scheduler
        # must still hand decode a wave every max_defer+1 waves. This is
        # the virtual-time starvation proof — wave COUNTS, no clocks.
        ws = make(budget=32, max_defer=2, boost=128)
        consecutive = 0
        for _ in range(100):
            plan = ws.plan(decode_rows=3, backlog=[10_000, 10_000])
            ws.note(plan)
            if plan.decode:
                consecutive = 0
            else:
                consecutive += 1
            assert consecutive <= ws.max_defer
        assert ws.max_defer_observed <= ws.max_defer
        assert ws.counts["boost"] > 0  # the bound was actually exercised

    def test_mixed_wave_always_carries_decode(self):
        ws = make(budget=32, boost=10_000)
        plan = ws.plan(decode_rows=2, backlog=[40])
        assert plan.kind == "mixed"
        assert plan.decode

    def test_pure_prefill_waves_do_not_charge_the_bound(self):
        # No decode rows = nobody to starve: full-width prefill waves
        # must not inflate max_defer_observed (they are the cold-start
        # drain path after the last decoder finishes).
        ws = make(budget=32, max_defer=1, boost=128)
        for _ in range(5):
            plan = ws.plan(decode_rows=0, backlog=[10_000])
            assert plan.kind == "prefill"
            ws.note(plan)
        assert ws.max_defer_observed == 0

    def test_max_defer_zero_disables_boost(self):
        ws = make(budget=32, max_defer=0, boost=128)
        plan = ws.plan(decode_rows=1, backlog=[10_000])
        assert plan.kind == "mixed"
        assert plan.decode


class TestAllotmentPolicy:
    def test_shortest_remaining_first(self):
        ws = make(budget=32, boost=10_000)
        plan = ws.plan(decode_rows=1, backlog=[100, 16, 20])
        # 16-token job fully served first, then the 20-token job gets
        # the remaining 16; the 100-token job waits.
        assert plan.allot == [0, 16, 16]

    def test_fifo_tiebreak_on_equal_remaining(self):
        ws = make(budget=16)
        plan = ws.plan(decode_rows=1, backlog=[16, 16])
        assert plan.allot == [16, 0]

    def test_empty_backlog_plans_pure_decode(self):
        ws = make()
        plan = ws.plan(decode_rows=2, backlog=[])
        assert plan.kind == "decode"
        assert plan.decode
        assert plan.allot == []

    def test_drained_jobs_get_zero(self):
        ws = make(budget=32)
        plan = ws.plan(decode_rows=1, backlog=[0, 10])
        assert plan.allot == [0, 10]


class TestAccounting:
    def test_note_and_snapshot_roundtrip(self):
        ws = make(budget=32, boost=128)
        ws.note(WavePlan("mixed", [16, 8], True))
        ws.note(WavePlan("boost", [128], False))
        ws.note(WavePlan("mixed", [4], True))
        snap = ws.snapshot()
        assert snap["counts"]["mixed"] == 2
        assert snap["counts"]["boost"] == 1
        assert snap["inline_tokens"] == 16 + 8 + 128 + 4
        assert snap["decode_defer"] == 0  # last wave carried decode
        assert snap["max_defer_observed"] == 1
        assert snap["budget"] == 32
        assert snap["max_defer"] == 2

    def test_boost_floor_is_inline_budget(self):
        ws = WaveScheduler(inline_budget=256, boost_tokens=64)
        assert ws.boost_tokens == 256
