"""Membership lifecycle plane (``policy/lifecycle.py``): the state
machine, digest gossip of lifecycle state, ``FleetView.forget`` /
left-marking, LEAVE wire + live-cluster semantics (cause-tagged
successor transitions, no failure detection, no auto-rejoin), warm
bootstrap with router hit-withholding, engine-level drain requeue, and
the pure autoscale recommender.

Deflake contract: lifecycle timers run on an injectable clock + wait
seam, so the state-machine tests here drive bootstrap in VIRTUAL time
(zero real sleeps); every live-cluster wait is a deadline-bounded poll.
"""

import time

import numpy as np
import pytest

from radixmesh_tpu.cache.mesh_cache import MeshCache
from radixmesh_tpu.cache.oplog import EXTENSION_KINDS, Oplog, OplogType, deserialize, serialize
from radixmesh_tpu.cache.repair_plane import RepairConfig, RepairPlane
from radixmesh_tpu.comm.inproc import InprocHub
from radixmesh_tpu.config import MeshConfig, NodeRole
from radixmesh_tpu.obs.fleet_plane import FleetPlane, FleetView, NodeDigest
from radixmesh_tpu.policy.lifecycle import (
    AutoscaleConfig,
    AutoscalePolicy,
    LifecycleConfig,
    LifecycleError,
    LifecyclePlane,
    LifecycleState,
    lifecycle_code,
    lifecycle_from_code,
)
from radixmesh_tpu.policy.topology import TopologyView, decode_view, encode_view

pytestmark = pytest.mark.quick


@pytest.fixture(autouse=True)
def fresh_hub():
    InprocHub.reset_default()
    yield
    InprocHub.reset_default()


def wait_for(pred, timeout=15.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def make_cluster(n_prefill=3, tick=0.05, digest=0.05, repair=True):
    prefill = [f"lp{i}" for i in range(n_prefill)]
    decode, router = ["ld0"], ["lr0"]
    nodes = []
    for addr in prefill + decode + router:
        cfg = MeshConfig(
            prefill_nodes=prefill, decode_nodes=decode, router_nodes=router,
            local_addr=addr, protocol="inproc", tick_interval_s=tick,
            gc_interval_s=60.0, failure_timeout_s=60.0,
        )
        nodes.append(MeshCache(cfg, pool=None).start())
    for n in nodes:
        assert n.wait_ready(timeout=10)
    ring = [n for n in nodes if n.role is not NodeRole.ROUTER]
    planes = [FleetPlane(n, interval_s=digest).start() for n in ring]
    repairs = []
    if repair:
        repairs = [
            RepairPlane(
                n,
                RepairConfig(
                    interval_s=0.05, age_threshold_s=0.2,
                    backoff_base_s=0.2, backoff_max_s=2.0,
                ),
                seed=0,
            ).start()
            for n in nodes
        ]
    return nodes, ring, nodes[-1], planes, repairs


def close_all(nodes, planes, repairs, lifecycles=()):
    for lc in lifecycles:
        lc.close()
    for r in repairs:
        r.close()
    for p in planes:
        p.close()
    for n in nodes:
        n.close()


def solo_mesh(addr="solo0"):
    """An UNSTARTED single-member mesh: enough MeshCache surface for a
    LifecyclePlane (label, fleet view, no-op broadcasts) without any
    transport — the state-machine and engine-drain tests need no ring."""
    cfg = MeshConfig(
        prefill_nodes=[addr], decode_nodes=[], router_nodes=[],
        local_addr=addr, protocol="inproc",
    )
    return MeshCache(cfg, pool=None)


class VirtualClock:
    """Deflake seam: lifecycle timers in virtual time, zero real sleeps."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def wait(self, dt: float) -> None:
        self.t += dt


class TestStateMachine:
    def test_legal_path_bootstrap_to_left(self):
        mesh = solo_mesh()
        lc = LifecyclePlane(mesh, bootstrap=True, cfg=LifecycleConfig(
            leave_retries=1, leave_confirm_s=0.0))
        assert lc.state is LifecycleState.BOOTSTRAPPING
        assert mesh.lifecycle is lc  # registered as the mesh's source
        lc._transition(LifecycleState.ACTIVE)
        assert not lc.is_departing
        stats = lc.drain(deadline_s=0.1)
        assert lc.state is LifecycleState.LEFT
        assert lc.is_departing
        assert stats["writeback_flushed"] is False  # no seam attached
        # Idempotent once LEFT.
        assert lc.drain(deadline_s=0.1) == stats

    def test_drain_step_5d_flushes_disk_ward(self):
        """PR 15: a runner exposing drain_flush_disk has its hot
        subtrees forced into durable extents as drain step 5d, with the
        commit verdict recorded — and a tier bug never wedges the LEAVE
        (crash-isolated like the black-box flush)."""
        mesh = solo_mesh()

        class DiskRunner:
            def begin_drain(self, retry_after_s=None):
                pass

            def drain_requeue(self):
                return 0

            def drain_wait_idle(self, deadline_s):
                return True

            def drain_flush(self):
                return 7, True

            def drain_flush_disk(self):
                return 3, True

        lc = LifecyclePlane(
            mesh, runner=DiskRunner(),
            cfg=LifecycleConfig(leave_retries=1, leave_confirm_s=0.0),
        )
        stats = lc.drain(deadline_s=0.1)
        assert lc.state is LifecycleState.LEFT
        assert stats["disk_spill_nodes"] == 3
        assert stats["disk_spill_committed"] is True

        class ExplodingDiskRunner(DiskRunner):
            def drain_flush_disk(self):
                raise RuntimeError("tier down")

        mesh2 = solo_mesh("solo-disk")
        lc2 = LifecyclePlane(
            mesh2, runner=ExplodingDiskRunner(),
            cfg=LifecycleConfig(leave_retries=1, leave_confirm_s=0.0),
        )
        stats2 = lc2.drain(deadline_s=0.1)
        assert lc2.state is LifecycleState.LEFT  # never wedged
        assert stats2["disk_spill_committed"] is False

    def test_failed_drain_releases_claim_for_retry(self):
        """A drain step that raises must not wedge the node in DRAINING
        forever: the claim releases so a retry can finish the exit
        (state stays DRAINING — nothing un-drains — and the retried
        sequence resumes from there)."""
        mesh = solo_mesh()
        calls = {"n": 0}

        def flaky_writeback():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("arena down")
            return 5

        lc = LifecyclePlane(
            mesh, writeback_fn=flaky_writeback,
            cfg=LifecycleConfig(leave_retries=1, leave_confirm_s=0.0),
        )
        with pytest.raises(RuntimeError, match="arena down"):
            lc.drain(deadline_s=0.1)
        assert lc.state is LifecycleState.DRAINING
        stats = lc.drain(deadline_s=0.1)  # retry completes the exit
        assert lc.state is LifecycleState.LEFT
        assert stats["writeback_tokens"] == 5

    def test_close_keeps_departing_guard_attached(self):
        """close() after a drain must NOT detach the plane from the
        mesh: the mesh keeps receiving for a beat on the exit path, and
        losing the is_departing guard would let a straggling exclusion
        view re-trigger the auto-rejoin JOIN."""
        mesh = solo_mesh()
        lc = LifecyclePlane(mesh, cfg=LifecycleConfig(
            leave_retries=1, leave_confirm_s=0.0))
        lc.drain(deadline_s=0.1)
        lc.close()
        assert mesh.lifecycle is lc and lc.is_departing
        # An un-drained plane detaches normally.
        mesh2 = solo_mesh("solo1")
        lc2 = LifecyclePlane(mesh2)
        lc2.close()
        assert mesh2.lifecycle is None

    def test_illegal_transitions_raise(self):
        lc = LifecyclePlane(solo_mesh())
        assert lc.state is LifecycleState.ACTIVE
        with pytest.raises(LifecycleError):
            lc._transition(LifecycleState.BOOTSTRAPPING)  # nothing un-joins
        with pytest.raises(LifecycleError):
            lc._transition(LifecycleState.ACTIVE)  # self-loop
        lc._transition(LifecycleState.DRAINING)
        with pytest.raises(LifecycleError):
            lc._transition(LifecycleState.ACTIVE)  # nothing un-drains

    def test_bootstrap_grace_expires_in_virtual_time(self):
        """No donor ever appears (cold boot): the node goes ACTIVE after
        the grace window — driven entirely on the injected clock."""
        clock = VirtualClock()
        lc = LifecyclePlane(
            solo_mesh(), bootstrap=True,
            cfg=LifecycleConfig(bootstrap_grace_s=5.0),
            clock=clock, wait=clock.wait,
        )
        for _ in range(4):
            lc.tick()
            assert lc.state is LifecycleState.BOOTSTRAPPING
            clock.wait(1.0)
        clock.wait(2.0)  # past the grace window
        lc.tick()
        assert lc.state is LifecycleState.ACTIVE
        assert lc.bootstrap_converge_s == pytest.approx(6.0)

    def test_cold_boot_with_converged_peers_skips_grace(self):
        """Cold cluster boot: every node starts BOOTSTRAPPING, so no
        ACTIVE donor exists — but every known peer replica already
        equals ours (empty == empty), so waiting out the grace window
        would withhold an empty fleet's (nonexistent) hits for nothing.
        Found by the end-to-end launch drive; virtual time."""
        clock = VirtualClock()
        mesh = solo_mesh()
        lc = LifecyclePlane(
            mesh, bootstrap=True,
            cfg=LifecycleConfig(bootstrap_grace_s=15.0),
            clock=clock, wait=clock.wait,
        )
        peer = NodeDigest(
            rank=5, role="prefill", seq=1, ts=1.0, epoch=0,
            fingerprint=mesh.tree.fingerprint_, tree_tokens=0,
            cache_hit_rate=0, pool_fill=0, host_fill=0, batch_occupancy=0,
            decode_ewma_s=0, waiting=0, decode_steps=0,
            lifecycle="bootstrapping",  # NOT donor-eligible
        )
        mesh.fleet.fold(peer)
        lc.tick()
        assert lc.state is LifecycleState.ACTIVE
        assert clock.t < 1.0  # no grace wait

    def test_bootstrap_converges_when_donor_fp_matches(self):
        """A donor digest with our exact fingerprint → ACTIVE on the
        next tick (virtual time; no probes needed)."""
        clock = VirtualClock()
        mesh = solo_mesh()
        lc = LifecyclePlane(
            mesh, bootstrap=True, cfg=LifecycleConfig(),
            clock=clock, wait=clock.wait,
        )
        donor = NodeDigest(
            rank=99, role="prefill", seq=1, ts=1.0, epoch=0,
            fingerprint=mesh.tree.fingerprint_, tree_tokens=0,
            cache_hit_rate=0, pool_fill=0, host_fill=0, batch_occupancy=0,
            decode_ewma_s=0, waiting=0, decode_steps=0, lifecycle="active",
        )
        mesh.fleet.fold(donor)
        lc.tick()
        assert lc.state is LifecycleState.ACTIVE
        assert lc.bootstrap_donor == 99

    def test_donor_choice_prefers_healthy_active_peers(self):
        mesh = solo_mesh()
        lc = LifecyclePlane(mesh, bootstrap=True)
        now = time.time()

        def digest(rank, lifecycle="active", ts=None):
            return NodeDigest(
                rank=rank, role="prefill", seq=1,
                ts=now if ts is None else ts, epoch=0,
                fingerprint=123 + rank, tree_tokens=0, cache_hit_rate=0,
                pool_fill=0, host_fill=0, batch_occupancy=0,
                decode_ewma_s=0, waiting=0, decode_steps=0,
                lifecycle=lifecycle, interval_s=5.0,
            )

        mesh.fleet.fold(digest(1, ts=now - 120.0))  # stale → sick
        mesh.fleet.fold(digest(2))                  # healthy ACTIVE
        mesh.fleet.fold(digest(3, lifecycle="bootstrapping"))  # not a donor
        mesh.fleet.fold(digest(4, lifecycle="draining"))       # not a donor
        assert lc.choose_donor() == 2


class TestDigestLifecycle:
    def test_tier_byte_packs_lifecycle_and_tier(self):
        for state in ("active", "bootstrapping", "draining", "left"):
            assert lifecycle_from_code(lifecycle_code(state)) == state
        d = NodeDigest(
            rank=7, role="decode", seq=2, ts=5.0, epoch=1, fingerprint=9,
            tree_tokens=1, cache_hit_rate=0.1, pool_fill=0.2, host_fill=0.0,
            batch_occupancy=0.3, decode_ewma_s=0.01, waiting=2,
            decode_steps=3, slo_tier=3, lifecycle="draining",
        )
        back = NodeDigest.decode(d.encode())
        assert back.lifecycle == "draining"
        assert back.slo_tier == 3

    def test_pre_lifecycle_v1_digest_decodes_full_byte_tier(self):
        """Rolling-upgrade compat, old→new direction: a v1 digest (full
        tier byte, no lifecycle nibble) decodes with its whole tier and
        lifecycle "active" — the state a pre-lifecycle node factually
        is in. (New→old is handled by the version bump: a v1 decoder
        rejects v2 instead of misreading the nibble as slo_tier=16.)"""
        assert lifecycle_from_code(0) == "active"
        d = NodeDigest(
            rank=1, role="prefill", seq=1, ts=1.0, epoch=0, fingerprint=0,
            tree_tokens=0, cache_hit_rate=0, pool_fill=0, host_fill=0,
            batch_occupancy=0, decode_ewma_s=0, waiting=0, decode_steps=0,
            slo_tier=3,
        )
        raw = bytearray(d.encode().tobytes())
        raw[1] = 1  # rewrite the version byte: a genuine v1 frame
        v1 = NodeDigest.decode(np.frombuffer(bytes(raw), dtype=np.int32))
        assert v1.lifecycle == "active"
        assert v1.slo_tier == 3

    def test_unknown_digest_version_rejected(self):
        d = NodeDigest(
            rank=1, role="prefill", seq=1, ts=1.0, epoch=0, fingerprint=0,
            tree_tokens=0, cache_hit_rate=0, pool_fill=0, host_fill=0,
            batch_occupancy=0, decode_ewma_s=0, waiting=0, decode_steps=0,
        )
        raw = bytearray(d.encode().tobytes())
        raw[1] = 9
        with pytest.raises(ValueError):
            NodeDigest.decode(np.frombuffer(bytes(raw), dtype=np.int32))

    def test_unknown_code_degrades_to_active(self):
        assert lifecycle_from_code(9) == "active"


class TestFleetViewForget:
    def _digest(self, rank, lifecycle="active", lag=0.0, fp=1, ts=10.0, seq=1):
        return NodeDigest(
            rank=rank, role="prefill", seq=seq, ts=ts, epoch=0,
            fingerprint=fp, tree_tokens=0, cache_hit_rate=0, pool_fill=0,
            host_fill=0, batch_occupancy=0, decode_ewma_s=0, waiting=0,
            decode_steps=0, replication_lag_s=lag, lifecycle=lifecycle,
        )

    def test_forget_drops_all_state_for_one_rank(self):
        fv = FleetView(now=lambda: 20.0)
        fv.fold(self._digest(1, lag=4.5, fp=111))
        fv.fold(self._digest(2, fp=222))
        assert 1 in fv.digests() and ("1-2" in fv.convergence()["pairs"])
        fv.forget(1)
        assert 1 not in fv.digests()
        assert "1-2" not in fv.convergence()["pairs"]
        assert fv.health().get(1) is None  # can't pin min_score anymore

    def test_rejoiner_does_not_inherit_old_lag_ewma(self):
        """The rejoin/decommission asymmetry fix: after forget-on-LEAVE,
        a reincarnation's first digest stands alone — the old
        replication-lag EWMA (which would have scored the fresh node
        sick) is gone, and its fingerprint folds fresh."""
        fv = FleetView(now=lambda: 20.0)
        fv.fold(self._digest(1, lag=99.0, fp=111, ts=10.0, seq=50))
        assert "replication_lag" in fv.health()[1]["reasons"]
        fv.forget(1)
        fv.mark_left(1)
        # The reincarnation restarts seq at 1 with a fresh clock.
        fv.fold(self._digest(1, lag=0.0, fp=0, ts=19.0, seq=1,
                             lifecycle="bootstrapping"))
        h = fv.health()[1]
        assert "replication_lag" not in h["reasons"]
        assert fv.lifecycle_of(1) == "bootstrapping"
        assert fv.digests()[1].fingerprint == 0  # folded fresh

    def test_left_mark_refuses_stragglers_until_rejoin(self):
        fv = FleetView(now=lambda: 20.0)
        fv.fold(self._digest(1, ts=10.0))
        fv.forget(1)
        fv.mark_left(1)
        assert fv.lifecycle_of(1) == "left"
        # A straggler from the departed incarnation is refused.
        assert not fv.fold(self._digest(1, lifecycle="draining", ts=11.0))
        assert 1 not in fv.digests()
        # A rejoiner's fresh state clears the mark.
        assert fv.fold(self._digest(1, lifecycle="bootstrapping", ts=12.0))
        assert fv.lifecycle_of(1) == "bootstrapping"
        assert fv.lifecycles()[1] == "bootstrapping"


class TestLeaveWire:
    def test_leave_round_trip_and_registration(self):
        assert OplogType.LEAVE in EXTENSION_KINDS
        view = TopologyView(epoch=7, alive=(0, 1, 3))
        op = Oplog(
            op_type=OplogType.LEAVE, origin_rank=2, logic_id=11, ttl=4,
            value=encode_view(view),
        )
        back = deserialize(serialize(op))
        assert back.op_type is OplogType.LEAVE
        assert back.origin_rank == 2
        assert decode_view(back.value) == view

    def test_live_leave_drops_node_without_failure_detection(self):
        """LEAVE on a live ring: every peer (router too) drops the
        leaver, the predecessor's successor transition is tagged
        cause=left (never dead), FleetView forgets it, and the leaver —
        being mid-drain — does NOT auto-rejoin when it sees its own
        exclusion."""
        nodes, ring, router_mesh, planes, repairs = make_cluster(repair=False)
        lifecycles = []
        try:
            target = ring[2]  # rank 2: its predecessor is ring[1]
            t_rank = target.rank
            wait_for(lambda: len(router_mesh.fleet.digests()) == len(ring))
            lc = LifecyclePlane(
                target, fleet_plane=planes[2],
                cfg=LifecycleConfig(leave_retries=2, leave_confirm_s=0.1),
            )
            lifecycles.append(lc)
            dead_before = sum(
                int(n._m_succ_trans["dead"].value) for n in nodes
            )
            lc.drain(deadline_s=1.0)
            assert lc.state is LifecycleState.LEFT
            survivors = [n for n in nodes if n is not target]
            assert wait_for(
                lambda: all(not n.view.contains(t_rank) for n in survivors)
            ), "peers never dropped the leaver"
            assert sum(
                int(n._m_succ_trans["dead"].value) for n in nodes
            ) == dead_before, "failure detection fired on a planned LEAVE"
            assert int(ring[1]._m_succ_trans["left"].value) >= 1, (
                "predecessor retarget not tagged cause=left"
            )
            assert router_mesh.fleet.lifecycle_of(t_rank) == "left"
            assert t_rank not in router_mesh.fleet.digests()
            # The leaver must NOT claw itself back in (auto-rejoin guard).
            time.sleep(0.3)
            assert all(
                not n.view.contains(t_rank) for n in survivors
            ), "drained node rejoined the view"
        finally:
            close_all(nodes, planes, repairs, lifecycles)


class TestWarmBootstrapLive:
    def test_rejoin_bootstraps_from_donor_and_router_withholds(self):
        """The full scale-in/scale-out cycle at test scale: drain rank 2,
        rejoin it cold, verify BOOTSTRAPPING gossip makes the router
        withhold hits while the bulk repair session fills the replica
        from a donor, then hits resume on convergence."""
        from radixmesh_tpu.router.cache_aware_router import CacheAwareRouter

        nodes, ring, router_mesh, planes, repairs = make_cluster()
        lifecycles = []
        joiner = jfleet = jrepair = None
        try:
            cr = CacheAwareRouter(router_mesh, router_mesh.cfg)
            cr.watch_topology()
            cr.finish_warm_up()
            target = ring[2]
            t_rank, t_addr = target.rank, target.cfg.local_addr
            rng = np.random.default_rng(0)
            keys = [
                rng.integers(0, 500, size=8).astype(np.int32)
                for _ in range(4)
            ]
            for k in keys:
                target.insert(k, np.arange(8, dtype=np.int32))
            assert wait_for(
                lambda: len({n.tree.fingerprint_ for n in nodes}) == 1
            )
            lc = LifecyclePlane(
                target, repair=repairs[2], fleet_plane=planes[2],
                cfg=LifecycleConfig(leave_retries=2, leave_confirm_s=0.1),
            )
            lifecycles.append(lc)
            lc.drain(deadline_s=1.0)
            survivors = [n for n in nodes if n is not target]
            assert wait_for(
                lambda: all(not n.view.contains(t_rank) for n in survivors)
            )
            planes[2].close()
            target.close()
            # -- cold rejoin -------------------------------------------
            joiner = MeshCache(target.cfg, pool=None).start()
            jrepair = RepairPlane(
                joiner,
                RepairConfig(
                    interval_s=0.05, age_threshold_s=0.2,
                    backoff_base_s=0.2, backoff_max_s=2.0,
                ),
                seed=0,
            ).start()
            jlc = LifecyclePlane(
                joiner, repair=jrepair,
                cfg=LifecycleConfig(
                    bootstrap_grace_s=10.0,
                    bootstrap_probe_interval_s=0.1,
                    bootstrap_round_budget=16,
                    tick_interval_s=0.05,
                ),
                bootstrap=True,
            )
            lifecycles.append(jlc)
            jfleet = FleetPlane(joiner, interval_s=0.05).start()
            jlc.fleet_plane = jfleet
            jlc.start()
            assert joiner.wait_ready(timeout=10)
            assert wait_for(lambda: router_mesh.view.contains(t_rank)), (
                "joiner never re-included"
            )
            # Router withholds hits while the replica bootstraps: the
            # rank-2 values it still holds must not route-hit to the
            # cold joiner.
            wh0 = cr.withheld_hits
            hits_cold = 0
            deadline = time.monotonic() + 20.0
            while (
                jlc.state is LifecycleState.BOOTSTRAPPING
                and time.monotonic() < deadline
            ):
                for k in keys:
                    res = cr.cache_aware_route(k)
                    if res.prefill_addr == t_addr and res.prefill_cache_hit:
                        hits_cold += 1
                time.sleep(0.02)
            assert wait_for(
                lambda: jlc.state is LifecycleState.ACTIVE, timeout=20.0
            ), "bootstrap never converged"
            assert hits_cold == 0, (
                f"{hits_cold} cache hits routed to a BOOTSTRAPPING node"
            )
            assert cr.withheld_hits > wh0, "withhold path never exercised"
            assert jlc.bootstrap_donor is not None
            assert jlc.bootstrap_rounds <= 16
            # The bulk session actually filled the replica.
            live = survivors + [joiner]
            assert wait_for(
                lambda: len({n.tree.fingerprint_ for n in live}) == 1
            ), "joiner never converged with the fleet"
            for k in keys:
                assert (
                    joiner.tree.match_prefix(k, split_partial=False).length
                    == len(k)
                )
            # Hits resume once ACTIVE gossips.
            assert wait_for(
                lambda: router_mesh.fleet.lifecycle_of(t_rank) == "active"
            )
            res = cr.cache_aware_route(keys[0])
            assert res.prefill_addr == t_addr and res.prefill_cache_hit
        finally:
            extra_nodes = [joiner] if joiner is not None else []
            close_all(
                [n for n in nodes if n is not nodes[2]] + extra_nodes,
                [p for i, p in enumerate(planes) if i != 2]
                + ([jfleet] if jfleet is not None else []),
                repairs + ([jrepair] if jrepair is not None else []),
                lifecycles,
            )


class TestEngineDrain:
    """Engine-level drain mechanics through the runner seams (the mesh
    LEAVE legs are covered above; here: admission closes retriably,
    queued + parked work requeues, decodes finish, hot prefixes flush
    through the PR 4 write-back lane)."""

    @pytest.fixture(scope="class")
    def tiny(self):
        import jax

        from radixmesh_tpu.models.llama import ModelConfig, init_params

        cfg = ModelConfig.tiny()
        return cfg, init_params(cfg, jax.random.PRNGKey(0))

    def _engine(self, tiny, **kw):
        from radixmesh_tpu.engine.engine import Engine

        cfg, params = tiny
        kw.setdefault("num_slots", 512)
        kw.setdefault("page_size", 4)
        kw.setdefault("max_batch", 2)
        kw.setdefault("host_cache_slots", 1024)
        kw.setdefault("kv_transfer_async", True)
        kw.setdefault("kv_transfer_chunk_tokens", 16)
        return Engine(cfg, params, **kw)

    def test_drain_requeues_queued_and_restoring_then_flushes(self, tiny):
        import threading

        from radixmesh_tpu.engine.request import RequestState, SamplingParams
        from radixmesh_tpu.server.http_frontend import EngineRunner

        eng = self._engine(tiny)
        prompt = list(range(1, 120))
        samp = SamplingParams(max_new_tokens=4)
        try:
            # Seed the host tier, then park a request mid-restore.
            eng.generate([prompt], samp)
            assert eng.tree.evict(100_000) > 0
            assert eng.kv_transfer.wait_host_ready()
            barrier = threading.Event()
            eng.kv_transfer.stage_barrier = barrier
            parked = eng.add_request(prompt, samp)
            for _ in range(3):
                eng.step()
            assert parked.state is RequestState.RESTORING
            queued = eng.add_request(list(range(300, 340)), samp)

            runner = EngineRunner(eng)  # not started: we drive directly
            runner.begin_drain()
            with pytest.raises(RuntimeError, match="draining"):
                runner.submit(list(range(400, 420)), samp)
            n = runner.drain_requeue()
            assert n == 2
            for req in (parked, queued):
                assert req.state is RequestState.FINISHED
                assert req.shed and req.shed_reason == "drain_requeue"
            barrier.set()
            eng.kv_transfer.stage_barrier = None
            # In-flight work (the cancelled ticket's staged chunks) runs
            # out under the deadline; then hot prefixes flush to host.
            deadline = time.monotonic() + 10
            while eng.has_work() and time.monotonic() < deadline:
                eng.step()
            flushed = eng.drain_flush_hot()
            assert flushed > 0
            assert eng.kv_transfer.wait_host_ready()
            assert eng.tree.evictable_size_ == 0  # nothing left hot
            assert eng.tree.protected_size_ == 0  # no leaked shields
        finally:
            eng.kv_transfer.close()

    def test_slo_runner_sheds_draining_with_retry_after(self, tiny):
        from radixmesh_tpu.slo import SLOConfig
        from radixmesh_tpu.slo.control import RequestShed
        from radixmesh_tpu.slo.runner import SLORunner

        eng = self._engine(tiny, kv_transfer_async=False)
        runner = SLORunner(eng, SLOConfig())
        runner.begin_drain(retry_after_s=2.5)
        with pytest.raises(RequestShed) as exc:
            runner.submit(list(range(10)), tenant="t0")
        assert exc.value.reason == "draining"
        assert exc.value.http_status == 503
        assert exc.value.retry_after_s == 2.5


class TestAutoscalePolicy:
    def _digest(self, rank, waiting=0, occ=0.0, tier=0, lifecycle="active",
                role="prefill", ts=100.0):
        return NodeDigest(
            rank=rank, role=role, seq=1, ts=ts, epoch=0, fingerprint=0,
            tree_tokens=0, cache_hit_rate=0, pool_fill=0, host_fill=0,
            batch_occupancy=occ, decode_ewma_s=0, waiting=waiting,
            decode_steps=0, slo_tier=tier, lifecycle=lifecycle,
            interval_s=5.0,
        )

    def _fleet(self, digests):
        fv = FleetView(now=lambda: 101.0)
        for d in digests:
            fv.fold(d)
        return fv

    def test_deep_queues_recommend_add(self):
        fv = self._fleet([self._digest(r, waiting=20, occ=0.9) for r in range(3)])
        rec = AutoscalePolicy().recommend(fv)
        assert rec["action"] == "add" and rec["reason"] == "queue_depth"

    def test_slo_degradation_recommends_add(self):
        fv = self._fleet([self._digest(r, waiting=1, tier=2) for r in range(3)])
        rec = AutoscalePolicy().recommend(fv)
        assert rec["action"] == "add" and rec["reason"] == "slo_degraded"

    def test_idle_fleet_recommends_remove_with_candidate(self):
        fv = self._fleet([
            self._digest(0, waiting=1, occ=0.2),
            self._digest(1, waiting=0, occ=0.1),
            self._digest(2, waiting=0, occ=0.0),
        ])
        rec = AutoscalePolicy().recommend(fv)
        assert rec["action"] == "remove"
        assert rec["remove_candidate"] == 2  # least loaded, highest rank

    def test_steady_fleet_holds(self):
        fv = self._fleet([self._digest(r, waiting=4, occ=0.5) for r in range(3)])
        assert AutoscalePolicy().recommend(fv)["action"] == "hold"

    def test_below_min_nodes_recommends_add(self):
        fv = self._fleet([self._digest(0)])
        rec = AutoscalePolicy(AutoscaleConfig(min_nodes=2)).recommend(fv)
        assert rec["action"] == "add" and rec["reason"] == "below_min_nodes"

    def test_no_telemetry_holds(self):
        """No serving digests = no signal: the policy must HOLD, not
        recommend scaling a healthy-but-quiet (gossip-disabled) fleet
        on noise. alive_ring alone is membership, not health."""
        fv = FleetView(now=lambda: 101.0)
        rec = AutoscalePolicy().recommend(fv, alive_ring=4)
        assert rec["action"] == "hold" and rec["reason"] == "no_telemetry"

    def test_bootstrapping_node_counts_as_capacity_routers_do_not(self):
        fv = self._fleet([
            self._digest(0, waiting=0, occ=0.0),
            self._digest(1, waiting=0, occ=0.0, lifecycle="bootstrapping"),
            self._digest(2, waiting=0, occ=0.0),
            self._digest(9, role="router"),
        ])
        rec = AutoscalePolicy().recommend(fv)
        assert rec["signals"]["serving_nodes"] == 3

    def test_pure_policy_no_side_effects(self):
        """The recommender is PURE: same view in, same verdict out, and
        the fleet view is untouched."""
        fv = self._fleet([self._digest(r, waiting=20) for r in range(3)])
        before = {r: d.seq for r, d in fv.digests().items()}
        r1 = AutoscalePolicy().recommend(fv)
        r2 = AutoscalePolicy().recommend(fv)
        assert r1 == r2
        assert {r: d.seq for r, d in fv.digests().items()} == before
