"""Pipeline-parallel SERVING (VERDICT round-2 weak #4 / next-step #5:
"PP is a shelf module ... nothing in engine/ or launch.py can serve
through it"). An Engine on a (pp, tp) mesh must produce the same tokens
as a single-device engine — prefill chunks and decode steps both run
through the GPipe schedule in ``parallel/pp_serving.py`` while the
scheduler/tree/publish machinery stays byte-identical. Runs on the
8-device virtual CPU mesh (conftest)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from radixmesh_tpu.engine.engine import Engine
from radixmesh_tpu.engine.request import SamplingParams
from radixmesh_tpu.models.llama import (
    ModelConfig,
    init_params,
    prefill_chunk_paged,
)
from radixmesh_tpu.parallel.pp_serving import (
    make_pp_serving_mesh,
    pp_forward_chunk,
    pp_pool_spec,
    shard_params_pp,
)

# fp32 so pipeline-vs-single parity is exact-token, not bf16-luck.
CFG = ModelConfig.tiny().replace(dtype=jnp.float32)
PARAMS = init_params(CFG, jax.random.PRNGKey(0))
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=6)


@pytest.fixture(scope="module")
def mesh():
    # pp=2 stages x tp=2 chips per stage.
    return make_pp_serving_mesh(pp=2, tp=2)


def test_pp_chunk_matches_reference(mesh):
    """pp_forward_chunk == prefill_chunk_paged numerics: ragged prior
    contexts, microbatched schedule, deferred KV scatter."""
    from jax.sharding import NamedSharding

    B, C, ps, maxp, num_slots = 4, 8, 4, 8, 256
    rng = np.random.default_rng(0)
    toks = rng.integers(1, CFG.vocab_size, (B, C)).astype(np.int32)
    prior = np.array([0, 4, 8, 12], np.int32)
    pos = prior[:, None] + np.arange(C, dtype=np.int32)[None]
    kvlen = prior + C
    pt = np.arange(B * maxp, dtype=np.int32).reshape(B, maxp)
    slots = pt[np.arange(B)[:, None], pos // ps] * ps + pos % ps
    pool0 = np.asarray(
        rng.normal(size=(2, CFG.n_layers, CFG.n_kv_heads, num_slots,
                         CFG.head_dim)),
        np.float32,
    )
    want_logits, want_pool = prefill_chunk_paged(
        PARAMS, CFG, jnp.asarray(toks), jnp.asarray(pos), jnp.asarray(pool0),
        jnp.asarray(slots), jnp.asarray(pt), jnp.asarray(kvlen),
        page_size=ps, kv_block_pages=4,
    )
    pparams = shard_params_pp(PARAMS, CFG, mesh)
    pool_sh = jax.device_put(
        jnp.asarray(pool0), NamedSharding(mesh, pp_pool_spec())
    )
    got_logits, got_pool = pp_forward_chunk(
        pparams, CFG, jnp.asarray(toks), jnp.asarray(pos), pool_sh,
        jnp.asarray(slots), jnp.asarray(pt), jnp.asarray(kvlen),
        page_size=ps, kv_block_pages=4, mesh=mesh, n_micro=2,
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(want_logits), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(got_pool), np.asarray(want_pool), rtol=2e-4, atol=2e-4
    )


def test_pp_chunk_kernel_engaged_matches_reference(mesh):
    """Same parity with the Pallas chunk kernel forced inside the pp
    stage bodies (interpret mode; VERDICT round-3 next-step #3): ragged
    prior contexts stream from each stage's local pool pages through the
    kernel, not the jnp hybrid."""
    from jax.sharding import NamedSharding

    B, C, ps, maxp, num_slots = 4, 8, 4, 8, 256
    rng = np.random.default_rng(21)
    toks = rng.integers(1, CFG.vocab_size, (B, C)).astype(np.int32)
    prior = np.array([0, 4, 8, 12], np.int32)
    pos = prior[:, None] + np.arange(C, dtype=np.int32)[None]
    kvlen = prior + C
    pt = np.arange(B * maxp, dtype=np.int32).reshape(B, maxp)
    slots = pt[np.arange(B)[:, None], pos // ps] * ps + pos % ps
    pool0 = np.asarray(
        rng.normal(size=(2, CFG.n_layers, CFG.n_kv_heads, num_slots,
                         CFG.head_dim)),
        np.float32,
    )
    want_logits, want_pool = prefill_chunk_paged(
        PARAMS, CFG, jnp.asarray(toks), jnp.asarray(pos), jnp.asarray(pool0),
        jnp.asarray(slots), jnp.asarray(pt), jnp.asarray(kvlen),
        page_size=ps, kv_block_pages=4,
    )
    pparams = shard_params_pp(PARAMS, CFG, mesh)
    pool_sh = jax.device_put(
        jnp.asarray(pool0), NamedSharding(mesh, pp_pool_spec())
    )
    got_logits, got_pool = pp_forward_chunk(
        pparams, CFG, jnp.asarray(toks), jnp.asarray(pos), pool_sh,
        jnp.asarray(slots), jnp.asarray(pt), jnp.asarray(kvlen),
        page_size=ps, kv_block_pages=4, mesh=mesh, n_micro=2,
        use_kernel=True, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got_logits), np.asarray(want_logits), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(got_pool), np.asarray(want_pool), rtol=2e-4, atol=2e-4
    )


def test_pp_engine_matches_single_device(mesh):
    """Same greedy tokens through a pp=2 x tp=2 engine as single-device:
    the pipeline changes placement and schedule, not semantics."""
    prompts = [
        np.random.default_rng(0).integers(1, CFG.vocab_size, 24).tolist(),
        np.random.default_rng(1).integers(1, CFG.vocab_size, 17).tolist(),
    ]
    single = Engine(CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4)
    want = single.generate(prompts, GREEDY)
    pp_eng = Engine(
        CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4,
        device_mesh=mesh,
    )
    got = pp_eng.generate(prompts, GREEDY)
    assert want == got


def test_pp_engine_prefix_hit(mesh):
    """Publish + prefix reuse work against the layer-sharded pool."""
    engine = Engine(
        CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4,
        device_mesh=mesh,
    )
    prompt = list(range(1, 25))
    engine.generate([prompt], GREEDY)
    cached_before = engine.stats.cached_tokens
    out = engine.generate([prompt + [100, 101]], GREEDY)[0]
    assert len(out) == 6
    assert engine.stats.cached_tokens - cached_before >= 20


def test_pp_validations(mesh):
    bad = CFG.replace(n_layers=3)  # 3 layers, pp=2
    with pytest.raises(ValueError, match="not divisible by"):
        Engine(bad, init_params(bad, jax.random.PRNGKey(0)), device_mesh=mesh)


def test_pp_int8_matches_single_device_int8(mesh):
    """int8 KV under pp: scales shard with their layers/heads
    (pp_scale_spec) and both prefill chunks and decode steps quantize
    in-layer exactly like the single-chip quantized paths — greedy tokens
    must match a single-device int8 engine."""
    prompts = [
        np.random.default_rng(7).integers(1, CFG.vocab_size, 22).tolist(),
        np.random.default_rng(8).integers(1, CFG.vocab_size, 15).tolist(),
    ]
    single = Engine(
        CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4,
        kv_quant="int8",
    )
    want = single.generate(prompts, GREEDY)
    pp_eng = Engine(
        CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4,
        device_mesh=mesh, kv_quant="int8",
    )
    got = pp_eng.generate(prompts, GREEDY)
    assert want == got
    # Prefix reuse against the quantized layer-sharded pool.
    cached0 = pp_eng.stats.cached_tokens
    out2 = pp_eng.generate([prompts[0] + [9, 8]], GREEDY)[0]
    assert len(out2) == 6
    assert pp_eng.stats.cached_tokens - cached0 >= 20


def test_pp_int8_fused_decode(mesh):
    """int8 + fused k-step pipeline decode compose."""
    prompt = list(range(1, 21))
    sampling = SamplingParams(temperature=0.0, max_new_tokens=8)
    single = Engine(
        CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4,
        kv_quant="int8",
    )
    want = single.generate([prompt], sampling)[0]
    pp_eng = Engine(
        CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4,
        device_mesh=mesh, kv_quant="int8", decode_steps_per_launch=4,
    )
    assert pp_eng.generate([prompt], sampling)[0] == want


class TestPPFusedDecode:
    """k-step fused decode through the pipeline (pp_decode_multi): one
    host round trip per k tokens under pp x tp, greedy tokens identical
    to a single-device engine stepping one token at a time."""

    def test_pp_engine_multi_step_matches_single_device(self, mesh):
        prompts = [
            np.random.default_rng(0).integers(1, CFG.vocab_size, 24).tolist(),
            np.random.default_rng(1).integers(1, CFG.vocab_size, 17).tolist(),
        ]
        sampling = SamplingParams(temperature=0.0, max_new_tokens=8)
        single = Engine(CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4)
        want = single.generate(prompts, sampling)
        pp_eng = Engine(
            CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4,
            device_mesh=mesh, decode_steps_per_launch=4,
        )
        got = pp_eng.generate(prompts, sampling)
        assert want == got

    def test_pp_decode_multi_matches_decode_multi(self, mesh):
        """Function-level: the rotating pipeline schedule emits the same
        greedy tokens as the single-chip fused loop on the same pool."""
        from jax.sharding import NamedSharding

        from radixmesh_tpu.models.llama import decode_multi
        from radixmesh_tpu.parallel.pp_serving import pp_decode_multi

        B, ps, maxp, k = 4, 4, 8, 4
        num_slots = B * maxp * ps
        rng = np.random.default_rng(5)
        # Seed the pool with a short real context per row (positions
        # 0..len-2 hold arbitrary KV; the fed token writes at len-1).
        pool_np = np.asarray(
            rng.normal(size=(2, CFG.n_layers, CFG.n_kv_heads, num_slots,
                             CFG.head_dim)),
            np.float32,
        )
        pool0 = jnp.asarray(pool_np)
        pt = np.arange(B * maxp, dtype=np.int32).reshape(B, maxp)
        lengths = np.asarray([3, 7, 12, 5], np.int32)
        tokens = rng.integers(1, CFG.vocab_size, B).astype(np.int32)
        zeros = jnp.zeros((B,), jnp.float32)
        ones = jnp.ones((B,), jnp.float32)
        topk0 = jnp.zeros((B,), jnp.int32)
        key = jax.random.PRNGKey(9)
        want, want_pool = decode_multi(
            PARAMS, CFG, jnp.asarray(tokens), pool0, jnp.asarray(pt),
            jnp.asarray(lengths), key, zeros, ones,
            page_size=ps, k_steps=k, top_ks=topk0,
        )
        pparams = shard_params_pp(PARAMS, CFG, mesh)
        pool_sh = jax.device_put(  # fresh copy: pool0 was donated above
            jnp.asarray(pool_np), NamedSharding(mesh, pp_pool_spec())
        )
        got, got_pool = pp_decode_multi(
            pparams, CFG, jnp.asarray(tokens), pool_sh, jnp.asarray(pt),
            jnp.asarray(lengths), key, zeros, ones, topk0,
            page_size=ps, k_steps=k, mesh=mesh,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        np.testing.assert_allclose(
            np.asarray(got_pool), np.asarray(want_pool), rtol=2e-4, atol=2e-4
        )

    def test_pp_decode_multi_kernel_engaged_token_exact(self, mesh):
        """VERDICT round-3 next-step #3: the pp stage bodies must run the
        Pallas fused decode kernel, not the jnp reference. Force
        ``use_kernel=True`` in interpret mode (the CPU-runnable execution
        of the SAME kernel program) and require token-exact agreement
        with the single-chip ``decode_multi`` — including untouched
        scratch redirection for warm-up/drain ticks."""
        from jax.sharding import NamedSharding

        from radixmesh_tpu.models.llama import decode_multi
        from radixmesh_tpu.parallel.pp_serving import pp_decode_multi

        B, ps, maxp, k = 4, 4, 8, 3
        # One extra page at the end is the scratch page warm-up/drain
        # writes are redirected into.
        num_slots = (B * maxp + 1) * ps
        scratch_slot = B * maxp * ps
        rng = np.random.default_rng(11)
        pool_np = np.asarray(
            rng.normal(size=(2, CFG.n_layers, CFG.n_kv_heads, num_slots,
                             CFG.head_dim)),
            np.float32,
        )
        pt = np.arange(B * maxp, dtype=np.int32).reshape(B, maxp)
        lengths = np.asarray([3, 7, 12, 5], np.int32)
        tokens = rng.integers(1, CFG.vocab_size, B).astype(np.int32)
        zeros = jnp.zeros((B,), jnp.float32)
        ones = jnp.ones((B,), jnp.float32)
        topk0 = jnp.zeros((B,), jnp.int32)
        key = jax.random.PRNGKey(13)
        want, want_pool = decode_multi(
            PARAMS, CFG, jnp.asarray(tokens), jnp.asarray(pool_np),
            jnp.asarray(pt), jnp.asarray(lengths), key, zeros, ones,
            page_size=ps, k_steps=k, top_ks=topk0,
        )
        pparams = shard_params_pp(PARAMS, CFG, mesh)
        pool_sh = jax.device_put(
            jnp.asarray(pool_np), NamedSharding(mesh, pp_pool_spec())
        )
        got, got_pool = pp_decode_multi(
            pparams, CFG, jnp.asarray(tokens), pool_sh, jnp.asarray(pt),
            jnp.asarray(lengths), key, zeros, ones, topk0,
            page_size=ps, k_steps=k, mesh=mesh,
            use_kernel=True, interpret=True, scratch_slot=scratch_slot,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # Real slots match the single-chip pool; only the scratch page
        # (which the single-chip run doesn't have) may differ.
        np.testing.assert_allclose(
            np.asarray(got_pool)[:, :, :, : B * maxp * ps],
            np.asarray(want_pool)[:, :, :, : B * maxp * ps],
            rtol=2e-4, atol=2e-4,
        )

    def test_pp_decode_multi_kernel_engaged_int8(self, mesh):
        """Kernel-engaged pp decode with an int8 pool: the aliased
        quantized fused kernel writes int8 KV + scales in place and
        matches the single-chip int8 fused loop token-exactly."""
        from jax.sharding import NamedSharding

        from radixmesh_tpu.models.llama import decode_multi
        from radixmesh_tpu.parallel.pp_serving import (
            pp_decode_multi,
            pp_scale_spec,
        )

        B, ps, maxp, k = 4, 4, 8, 3
        num_slots = (B * maxp + 1) * ps
        scratch_slot = B * maxp * ps
        rng = np.random.default_rng(17)
        pool_np = rng.integers(
            -127, 128,
            (2, CFG.n_layers, CFG.n_kv_heads, num_slots, CFG.head_dim),
        ).astype(np.int8)
        scale_np = np.abs(
            rng.normal(size=(2, CFG.n_layers, CFG.n_kv_heads, num_slots))
        ).astype(np.float32) * 0.01
        pt = np.arange(B * maxp, dtype=np.int32).reshape(B, maxp)
        lengths = np.asarray([3, 7, 12, 5], np.int32)
        tokens = rng.integers(1, CFG.vocab_size, B).astype(np.int32)
        zeros = jnp.zeros((B,), jnp.float32)
        ones = jnp.ones((B,), jnp.float32)
        topk0 = jnp.zeros((B,), jnp.int32)
        key = jax.random.PRNGKey(19)
        want, want_pool, want_scale = decode_multi(
            PARAMS, CFG, jnp.asarray(tokens), jnp.asarray(pool_np),
            jnp.asarray(pt), jnp.asarray(lengths), key, zeros, ones,
            page_size=ps, k_steps=k, top_ks=topk0,
            kv_scale=jnp.asarray(scale_np),
        )
        pparams = shard_params_pp(PARAMS, CFG, mesh)
        pool_sh = jax.device_put(
            jnp.asarray(pool_np), NamedSharding(mesh, pp_pool_spec())
        )
        scale_sh = jax.device_put(
            jnp.asarray(scale_np), NamedSharding(mesh, pp_scale_spec())
        )
        got, got_pool, got_scale = pp_decode_multi(
            pparams, CFG, jnp.asarray(tokens), pool_sh, jnp.asarray(pt),
            jnp.asarray(lengths), key, zeros, ones, topk0,
            page_size=ps, k_steps=k, mesh=mesh, kv_scale=scale_sh,
            use_kernel=True, interpret=True, scratch_slot=scratch_slot,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        real = slice(0, B * maxp * ps)
        np.testing.assert_array_equal(
            np.asarray(got_pool)[:, :, :, real],
            np.asarray(want_pool)[:, :, :, real],
        )
        np.testing.assert_allclose(
            np.asarray(got_scale)[:, :, :, real],
            np.asarray(want_scale)[:, :, :, real],
            rtol=1e-6, atol=1e-6,
        )

    def test_pp_multi_step_stochastic_rows_complete(self, mesh):
        """Sampled rows (temperature > 0) run the same fused schedule;
        output length and token-range sanity (distribution parity with
        the single-chip sampler is pinned by its own rejection tests)."""
        pp_eng = Engine(
            CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4,
            device_mesh=mesh, decode_steps_per_launch=4,
        )
        prompt = list(range(1, 20))
        out = pp_eng.generate(
            [prompt], SamplingParams(temperature=0.8, top_p=0.9,
                                     max_new_tokens=8)
        )[0]
        assert len(out) == 8
        assert all(0 <= t < CFG.vocab_size for t in out)


class TestPPSpecDecode:
    """Speculative decoding under pp: the verify pass is a C=γ+1 chunk
    through pp_forward_chunk. Greedy replay must equal plain decode
    (speculation changes cost, never tokens)."""

    def test_pp_spec_greedy_replay_matches_plain(self, mesh):
        prompt = list(range(1, 28))
        sampling = SamplingParams(temperature=0.0, max_new_tokens=8)
        plain = Engine(CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4)
        want = plain.generate([prompt], sampling)[0]
        spec = Engine(
            CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4,
            device_mesh=mesh, spec_decode_tokens=3,
        )
        # First serve: mostly n-gram drafts. Replay: the radix tree holds
        # the previous generation — near-perfect tree drafts.
        first = spec.generate([prompt], sampling)[0]
        assert first == want
        replay = spec.generate([prompt], sampling)[0]
        assert replay == want
        assert spec.stats.spec_accepted > 0, (
            "replay never accepted a draft through the pp verify chunk"
        )

    def test_pp_spec_single_stream(self, mesh):
        """max_batch=1 (doesn't split into pp microbatches): speculation
        must still run via the one-wave fallback — single-stream latency
        is its prime use case."""
        prompt = list(range(1, 26))
        sampling = SamplingParams(temperature=0.0, max_new_tokens=6)
        plain = Engine(CFG, PARAMS, num_slots=1024, page_size=4, max_batch=1)
        want = plain.generate([prompt], sampling)[0]
        spec = Engine(
            CFG, PARAMS, num_slots=1024, page_size=4, max_batch=1,
            device_mesh=mesh, spec_decode_tokens=3,
        )
        assert spec.generate([prompt], sampling)[0] == want
        replay = spec.generate([prompt], sampling)[0]
        assert replay == want
        assert spec.stats.spec_accepted > 0

    def test_pp_spec_int8(self, mesh):
        """pp + int8 + speculation compose: the verify chunk quantizes
        in-layer (the see-what-you-store invariant) so a replay through a
        quantized pipeline pool matches a single-device int8 engine."""
        prompt = list(range(5, 32))
        sampling = SamplingParams(temperature=0.0, max_new_tokens=6)
        plain = Engine(
            CFG, PARAMS, num_slots=1024, page_size=4, max_batch=2,
            kv_quant="int8",
        )
        want = plain.generate([prompt], sampling)[0]
        spec = Engine(
            CFG, PARAMS, num_slots=1024, page_size=4, max_batch=2,
            device_mesh=mesh, kv_quant="int8", spec_decode_tokens=3,
        )
        assert spec.generate([prompt], sampling)[0] == want
        assert spec.generate([prompt], sampling)[0] == want  # replay
        assert spec.stats.spec_accepted > 0


class TestPPStorm:
    """Random request storm against a pp x tp engine: admission waves,
    cancellation, preemption on a tight pool, mixed sampling — the same
    invariants the single-chip storms enforce must hold with the layer-
    sharded pool and pipeline schedule."""

    @pytest.mark.parametrize("seed", [3, 14])
    def test_pp_request_storm_drains_and_balances(self, mesh, seed):
        rng = np.random.default_rng(seed)
        eng = Engine(
            CFG, PARAMS, num_slots=128, page_size=4, max_batch=4,
            max_seq_len=128, device_mesh=mesh,
            decode_steps_per_launch=2 if seed == 3 else 1,
            spec_decode_tokens=3 if seed == 14 else 0,
        )
        live, done = [], []
        for _ in range(40):
            roll = rng.random()
            if roll < 0.35 and len(live) < 8:
                n = int(rng.integers(3, 24))
                prompt = rng.integers(1, CFG.vocab_size, n).tolist()
                temp = 0.0 if rng.random() < 0.7 else 0.8
                live.append(
                    eng.add_request(
                        prompt,
                        SamplingParams(
                            temperature=temp,
                            max_new_tokens=int(rng.integers(2, 10)),
                        ),
                    )
                )
            elif roll < 0.45 and live:
                eng.cancel(live[int(rng.integers(0, len(live)))].rid)
            elif eng.has_work():
                eng.step()
            still = []
            for r in live:
                (done if r.state.value == "finished" else still).append(r)
            live = still
        while eng.has_work():
            eng.step()
        done.extend(live)
        for r in done:
            assert r.state.value == "finished", r
            if not r.cancelled:
                assert len(r.output_tokens) == r.sampling.max_new_tokens
            assert all(0 <= t < CFG.vocab_size for t in r.output_tokens)
        tree_tokens = eng.tree.total_size()
        assert eng.pool.free_slots + tree_tokens + 4 == eng.pool.num_slots


class TestPPComposition:
    """The remaining engine subsystems compose with pp serving: the
    host-RAM cache tier and checkpoint/restore both act on slot ids and
    gathered arrays — GSPMD handles the layer-sharded placement."""

    def test_pp_engine_host_cache_tier(self, mesh):
        """A prefix evicted from a tiny layer-sharded pool restores from
        host RAM and still hits."""
        from radixmesh_tpu.obs.metrics import get_registry

        eng = Engine(
            CFG, PARAMS, num_slots=128, page_size=4, max_batch=1,
            max_seq_len=96, host_cache_slots=1024, device_mesh=mesh,
            name="pp-hicache",
        )
        a = list(range(1, 60))
        b = list(range(100, 160))
        eng.generate([a], max_steps=30)
        eng.generate([b], max_steps=30)  # evicts much of a's KV to host
        eng.generate([a], max_steps=30)  # must hit via host restore
        assert eng.stats.cached_tokens > 0
        snap = get_registry().snapshot()
        assert snap.get("radixmesh_hicache_backup_tokens_total", 0) > 0
        assert snap.get("radixmesh_hicache_restore_tokens_total", 0) > 0

    def test_pp_engine_tree_snapshot_restore(self, mesh, tmp_path):
        """Serve → snapshot the tree+pool → restore into a FRESH pp
        engine → the restored prefix is a cache hit with identical
        continuation tokens."""
        from radixmesh_tpu.checkpoint import load_tree, save_tree

        eng = Engine(
            CFG, PARAMS, num_slots=1024, page_size=4, max_batch=2,
            device_mesh=mesh,
        )
        prompt = list(range(1, 30))
        out1 = eng.generate([prompt], GREEDY)[0]
        path = str(tmp_path / "pp-tree.json")
        save_tree(path, eng.tree, pool=eng.pool)

        eng2 = Engine(
            CFG, PARAMS, num_slots=1024, page_size=4, max_batch=2,
            device_mesh=mesh,
        )
        load_tree(path, eng2.tree, pool=eng2.pool)
        cached0 = eng2.stats.cached_tokens
        out2 = eng2.generate([prompt + [7, 8]], GREEDY)[0]
        assert len(out2) == 6
        assert eng2.stats.cached_tokens - cached0 >= 24
        # Same weights + restored KV: a plain re-serve of the original
        # prompt replays the original continuation exactly.
        assert eng2.generate([prompt], GREEDY)[0] == out1
