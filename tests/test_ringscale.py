"""Ring-scale regression (VERDICT round-3 missing #4): a LARGE flat ring
must still converge, and its lap latency must scale ~linearly — the
measured basis for the ARCHITECTURE.md hierarchy-crossover analysis
(the reference's open question, README.md:57)."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

from ringscale import run_ring  # noqa: E402


def test_large_ring_converges_and_laps_scale():
    small = run_ring(6, n_inserts=15, n_laps=10)
    big = run_ring(24, n_inserts=15, n_laps=10)
    # Convergence is exact (run_ring raises on timeout); scaling is the
    # property: a 4x ring must not blow lap latency up superlinearly
    # (generous 3x-per-2x bound — thread-scheduling noise at 24 in-proc
    # nodes is real) and per-insert ring traffic is exactly O(N).
    assert big["lap_p50_ms"] < small["lap_p50_ms"] * 12
    assert big["ring_bytes_per_insert"] == small["frame_bytes"] * 23
    assert big["applies_per_insert"] == 23
