"""Ring-scale regression (VERDICT round-3 missing #4 → round-4 hier
implementation): a LARGE ring must still converge in BOTH topologies,
and per-insert ring traffic must match the topology's frame model — the
measured basis for ARCHITECTURE.md's hierarchy-crossover section (the
reference's open question, README.md:57).

Each sweep runs in a SUBPROCESS: a 24-node tcp-py ring is ~120 threads
and ~50 sockets, and carrying that churn inside the pytest process
destabilized later XLA compiles (segfault at ~91% of the suite, twice).
"""

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_DRIVER = """
import json, sys
sys.path.insert(0, {repo!r})
sys.path.insert(0, {scripts!r})
import jax
jax.config.update("jax_platforms", "cpu")
from ringscale import run_ring
print(json.dumps(run_ring({n}, n_inserts=15, n_probes=8, topology={topo!r})))
"""


def run_ring_isolated(n: int, topology: str) -> dict:
    proc = subprocess.run(
        [sys.executable, "-c", _DRIVER.format(
            repo=_REPO, scripts=os.path.join(_REPO, "scripts"),
            n=n, topo=topology,
        )],
        stdout=subprocess.PIPE, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, f"sweep N={n}/{topology} failed"
    return json.loads(proc.stdout.decode().strip().splitlines()[-1])


def test_large_flat_ring_converges_and_props_scale():
    small = run_ring_isolated(6, "ring")
    big = run_ring_isolated(24, "ring")
    # Convergence is exact (run_ring raises on timeout); scaling is the
    # property: a 4x ring must not blow propagation latency up
    # superlinearly (generous 3x-per-2x bound — thread-scheduling noise
    # at 24 in-proc nodes is real) and per-insert traffic is exactly O(N):
    # N frames counting the lap-return hop to the origin. The MEASURED
    # send counters must match the model exactly — a forwarding bug that
    # duplicates or re-floods frames shows up here, not in the model.
    assert big["prop_p50_ms"] < small["prop_p50_ms"] * 12
    assert small["measured_frames_per_insert"] == small["frames_per_insert"] == 6
    assert big["measured_frames_per_insert"] == big["frames_per_insert"] == 24
    assert big["ring_bytes_per_insert"] == big["frame_bytes"] * 24


def test_large_hier_ring_converges_with_expected_traffic():
    r = run_ring_isolated(24, "hier")
    # auto group size at N=24 is 5 → 5 groups (4 of 5, 1 of 4): frames =
    # one full lap per group (24, return hops included) + one spine lap
    # (5). Measured sends must agree — circulation regressions
    # (double-bridge, spine re-flood) land here.
    assert r["group_size"] == 5
    assert r["frames_per_insert"] == 24 + 5
    assert r["measured_frames_per_insert"] == 29
