"""Hierarchical (groups + leader spine) replication topology tests.

The reference's open roadmap item — "better topo if nodes over some
number (like 50?)" (``/root/reference/README.md:57``) — implemented in
``policy/hierarchy.py`` + ``MeshCache._circulate``. These tests prove the
same correctness properties the flat ring's suite proves (convergence,
conflict resolution, router attribution, distributed GC, DELETE/RESET,
elastic failover) hold when oplogs propagate group-lap → spine →
injected group laps instead of one O(N) lap.
"""

import time

import numpy as np
import pytest

from radixmesh_tpu.cache.kv_pool import PagedKVPool
from radixmesh_tpu.cache.mesh_cache import MeshCache
from radixmesh_tpu.cache.oplog import (
    NodeKey,
    Oplog,
    OplogType,
    deserialize,
    patched_frame,
    serialize,
)
from radixmesh_tpu.comm.inproc import InprocHub
from radixmesh_tpu.config import MeshConfig, NodeRole
from radixmesh_tpu.policy.hierarchy import HierPlan, auto_group_size


def wait_for(pred, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


@pytest.fixture(autouse=True)
def fresh_hub():
    InprocHub.reset_default()
    yield
    InprocHub.reset_default()


# ----------------------------------------------------------------------
# pure partition math
# ----------------------------------------------------------------------


class TestHierPlan:
    def test_static_partition(self):
        p = HierPlan(ring_size=9, group_size=3)
        assert p.n_static_groups == 3
        assert [p.group_of(r) for r in range(9)] == [0, 0, 0, 1, 1, 1, 2, 2, 2]
        assert list(p.group_ranks(2)) == [6, 7, 8]
        assert p.same_group(3, 5) and not p.same_group(2, 3)

    def test_ragged_tail_group(self):
        p = HierPlan(ring_size=7, group_size=3)
        assert p.n_static_groups == 3
        assert list(p.group_ranks(2)) == [6]

    def test_leaders_and_successors_full_view(self):
        p = HierPlan(ring_size=9, group_size=3)
        alive = range(9)
        assert [p.leader_of(g, alive) for g in range(3)] == [0, 3, 6]
        assert p.is_leader(0, alive) and not p.is_leader(1, alive)
        assert p.group_successor(0, alive) == 1
        assert p.group_successor(2, alive) == 0  # wraps within the group
        assert p.group_successor(8, alive) == 6
        assert p.spine_successor(0, alive) == 3
        assert p.spine_successor(6, alive) == 0  # spine wraps over groups
        assert p.group_ttl(4, alive) == 3
        assert p.spine_ttl(alive) == 3

    def test_holes_shrink_but_never_repartition(self):
        p = HierPlan(ring_size=9, group_size=3)
        alive = [0, 2, 4, 5, 8]  # 1,3,6,7 dead
        # Leadership moves to the lowest ALIVE rank of the static group.
        assert p.leader_of(0, alive) == 0
        assert p.leader_of(1, alive) == 4
        assert p.leader_of(2, alive) == 8
        assert p.is_leader(4, alive) and not p.is_leader(5, alive)
        assert p.group_successor(0, alive) == 2
        assert p.group_successor(2, alive) == 0
        assert p.group_successor(8, alive) is None  # alone in its group
        assert p.spine_successor(8, alive) == 0
        assert p.group_ttl(4, alive) == 2
        assert p.spine_ttl(alive) == 3

    def test_dead_group_skipped_on_spine(self):
        p = HierPlan(ring_size=9, group_size=3)
        alive = [0, 1, 2, 6, 7, 8]  # group 1 entirely dead
        assert p.nonempty_groups(alive) == [0, 2]
        assert p.spine_successor(0, alive) == 6
        assert p.spine_successor(6, alive) == 0
        assert p.spine_ttl(alive) == 2

    def test_degenerate_single_group(self):
        p = HierPlan(ring_size=4, group_size=8)
        alive = range(4)
        assert p.spine_successor(0, alive) is None
        assert p.group_successor(1, alive) == 2

    def test_auto_group_size(self):
        assert auto_group_size(50) == 7
        assert auto_group_size(9) == 3
        assert auto_group_size(2) == 2  # floor at 2

    def test_validation(self):
        with pytest.raises(ValueError):
            HierPlan(ring_size=9, group_size=1)
        p = HierPlan(ring_size=9, group_size=3)
        with pytest.raises(ValueError):
            p.group_of(9)


# ----------------------------------------------------------------------
# wire scope flag
# ----------------------------------------------------------------------


class TestSpineWire:
    def test_spine_flag_round_trips(self):
        op = Oplog(
            op_type=OplogType.INSERT,
            origin_rank=4,
            logic_id=7,
            ttl=3,
            key=np.asarray([1, 2, 3], dtype=np.int32),
            value=np.asarray([10, 11, 12], dtype=np.int32),
            value_rank=4,
            spine=True,
        )
        back = deserialize(serialize(op))
        assert back.spine is True
        assert back == op

    def test_patched_frame_rescopes_in_place(self):
        op = Oplog(
            op_type=OplogType.INSERT,
            origin_rank=2,
            logic_id=5,
            ttl=9,
            key=np.asarray([5, 6], dtype=np.int32),
            value=np.asarray([1, 2], dtype=np.int32),
            value_rank=2,
            spine=True,
        )
        data = serialize(op)
        back = deserialize(patched_frame(data, ttl=4, spine=False, value_rank=8))
        assert back.ttl == 4
        assert back.spine is False
        assert back.value_rank == 8
        # Untouched fields (including u24-packed arrays) survive the patch.
        np.testing.assert_array_equal(back.key, op.key)
        np.testing.assert_array_equal(back.value, op.value)
        assert back.logic_id == 5 and back.origin_rank == 2

    def test_patched_frame_rejects_pre_v3_scope_patch(self):
        from radixmesh_tpu.cache.oplog import set_emit_version

        op = Oplog(op_type=OplogType.TICK, origin_rank=0, logic_id=1, ttl=2)
        set_emit_version(2)
        try:
            data = serialize(op)
        finally:
            set_emit_version(3)
        with pytest.raises(ValueError):
            patched_frame(data, spine=True)
        # TTL-only patches still work on old frames.
        assert deserialize(patched_frame(data, ttl=1)).ttl == 1


# ----------------------------------------------------------------------
# live hier cluster
# ----------------------------------------------------------------------


class HierCluster:
    """6 prefill + 3 decode ring members (3 groups of 3) + 1 router."""

    def __init__(
        self,
        n_prefill=6,
        n_decode=3,
        group_size=3,
        num_slots=256,
        failure_timeout_s=10.0,
    ):
        prefill = [f"hp{i}" for i in range(n_prefill)]
        decode = [f"hd{i}" for i in range(n_decode)]
        router = ["hr0"]
        self.nodes: list[MeshCache] = []
        for addr in prefill + decode + router:
            cfg = MeshConfig(
                prefill_nodes=prefill,
                decode_nodes=decode,
                router_nodes=router,
                local_addr=addr,
                protocol="inproc",
                topology="hier",
                group_size=group_size,
                tick_interval_s=0.05,
                gc_interval_s=30.0,  # tests drive GC explicitly
                failure_timeout_s=failure_timeout_s,
                startup_grace_s=failure_timeout_s,
            )
            pool = (
                None
                if cfg.local_role is NodeRole.ROUTER
                else PagedKVPool(
                    num_slots=num_slots, num_layers=1, num_kv_heads=1, head_dim=2
                )
            )
            self.nodes.append(MeshCache(cfg, pool=pool))
        for n in self.nodes:
            n.start()

    @property
    def ring_nodes(self):
        return [n for n in self.nodes if n.role is not NodeRole.ROUTER]

    @property
    def router(self):
        return next(n for n in self.nodes if n.role is NodeRole.ROUTER)

    def node(self, rank):
        return self.nodes[rank]

    def wait_ready(self):
        for n in self.nodes:
            assert n.wait_ready(timeout=10), f"node {n.rank} never became ready"

    def close(self):
        for n in self.nodes:
            n.close()


@pytest.fixture
def hier_cluster():
    c = HierCluster()
    c.wait_ready()
    yield c
    c.close()


def insert_with_pool(node: MeshCache, key) -> np.ndarray:
    slots = node.pool.alloc(len(key))
    assert slots is not None
    node.insert(key, slots)
    return slots


class TestHierStartup:
    def test_all_nodes_ready_including_router(self, hier_cluster):
        # wait_ready in the fixture is the real assertion; spot-check the
        # plan wiring: 3 groups, leaders 0/3/6, spine targets set.
        n0 = hier_cluster.node(0)
        assert n0.hier is not None and n0._spine_rank == 3
        assert hier_cluster.node(3)._spine_rank == 6
        assert hier_cluster.node(6)._spine_rank == 0
        assert hier_cluster.node(1)._spine_rank is None  # not a leader
        assert hier_cluster.node(1)._succ_rank == 2
        assert hier_cluster.node(2)._succ_rank == 0  # wraps within group


class TestHierReplication:
    @pytest.mark.parametrize("writer_rank", [0, 1, 4, 8])
    def test_insert_reaches_every_group_and_the_router(
        self, hier_cluster, writer_rank
    ):
        # Leader origins (0), plain members (1, 4), and the tail group's
        # last member (8) must all reach all 9 ring nodes + the router.
        key = [writer_rank + 1, 2, 3]
        writer = hier_cluster.node(writer_rank)
        insert_with_pool(writer, key)
        for n in hier_cluster.ring_nodes:
            assert wait_for(lambda n=n: n.match_prefix(key).length == 3), (
                f"rank {n.rank} never converged (writer {writer_rank})"
            )
            assert all(v.rank == writer_rank for v in n.match_prefix(key).values)
        route = None

        def routed():
            nonlocal route
            route = hier_cluster.router.match_prefix(key)
            want = writer_rank if writer_rank < 6 else -1
            dwant = writer_rank if writer_rank >= 6 else -1
            return route.prefill_rank == want and route.decode_rank == dwant

        assert wait_for(routed), f"router never attributed: {route}"

    def test_leaders_bridge_once_per_op(self, hier_cluster):
        writer = hier_cluster.node(1)  # group 0, non-leader
        before = hier_cluster.node(0).metrics.get("oplogs_sent", 0)
        bridged0 = hier_cluster.node(0)._m_bridged.value
        insert_with_pool(writer, [7, 7, 7])
        assert wait_for(
            lambda: hier_cluster.node(8).match_prefix([7, 7, 7]).length == 3
        )
        # Group 0's leader bridged exactly this one INSERT (ticks also
        # bridge, so allow the heartbeat's contribution but require at
        # least one new bridge).
        assert hier_cluster.node(0)._m_bridged.value > bridged0
        del before

    def test_multi_writer_conflict_converges_to_lowest_rank_across_groups(
        self, hier_cluster
    ):
        key = [5, 5, 5]
        # Writers in three different groups race on the same key.
        for rank in (7, 4, 0):
            insert_with_pool(hier_cluster.node(rank), key)
        for n in hier_cluster.ring_nodes:
            assert wait_for(
                lambda n=n: n.match_prefix(key).length == 3
                and all(v.rank == 0 for v in n.match_prefix(key).values)
            ), f"rank {n.rank} did not converge to rank 0's value"

    def test_delete_and_reset_replicate(self, hier_cluster):
        key = [6, 6, 6]
        writer = hier_cluster.node(4)
        insert_with_pool(writer, key)
        for n in hier_cluster.ring_nodes:
            assert wait_for(lambda n=n: n.match_prefix(key).length == 3)
        assert writer.delete(key)
        for n in hier_cluster.ring_nodes:
            assert wait_for(lambda n=n: n.match_prefix(key).length == 0), (
                f"rank {n.rank} kept the deleted key"
            )
        insert_with_pool(hier_cluster.node(2), [1, 2])
        assert wait_for(
            lambda: hier_cluster.node(8).match_prefix([1, 2]).length == 2
        )
        hier_cluster.node(2).reset_all()
        for n in hier_cluster.ring_nodes:
            assert wait_for(lambda n=n: n.match_prefix([1, 2]).length == 0)


class TestHierGC:
    def test_cross_group_gc_aggregates_votes_and_frees(self, hier_cluster):
        key = [9, 8, 7]
        winner = hier_cluster.node(0)  # group 0
        loser = hier_cluster.node(5)  # group 1
        insert_with_pool(winner, key)
        loser_slots = insert_with_pool(loser, key)
        nk = NodeKey(key, loser.rank)
        assert wait_for(
            lambda: all(nk in n.dup_nodes for n in hier_cluster.ring_nodes)
        ), "duplicate never recorded everywhere"
        free_before = loser.pool.free_slots
        loser.run_gc_round()
        assert wait_for(
            lambda: loser.pool.free_slots == free_before + len(key), timeout=15
        ), "loser's duplicate slots never freed (vote aggregation broke?)"
        assert wait_for(
            lambda: all(nk not in n.dup_nodes for n in hier_cluster.ring_nodes)
        ), "GC_EXEC did not retire the duplicate everywhere"
        assert all(v.rank == 0 for v in loser.match_prefix(key).values)
        del loser_slots

    def test_gc_refused_while_a_remote_group_holds_a_lock(self, hier_cluster):
        key = [4, 4, 4]
        winner, loser = hier_cluster.node(0), hier_cluster.node(3)
        insert_with_pool(winner, key)
        insert_with_pool(loser, key)
        nk = NodeKey(key, loser.rank)
        assert wait_for(
            lambda: all(nk in n.dup_nodes for n in hier_cluster.ring_nodes)
        )
        # A reader in a THIRD group locks the path: its group's tally must
        # come back short and block unanimity.
        reader = hier_cluster.node(7)
        res = reader.match_prefix(key)
        reader.inc_lock_ref(res.last_node)
        free_before = loser.pool.free_slots
        loser.run_gc_round()
        time.sleep(1.0)
        assert loser.pool.free_slots == free_before, "GC freed despite a lock"
        assert nk in loser.dup_nodes
        reader.dec_lock_ref(res.last_node)
        loser.run_gc_round()
        assert wait_for(
            lambda: loser.pool.free_slots == free_before + len(key), timeout=15
        )


class TestHierFailover:
    def test_leader_death_promotes_and_replication_continues(self):
        c = HierCluster(failure_timeout_s=0.6)
        try:
            c.wait_ready()
            # Kill group 1's leader (rank 3) like a crash.
            c.node(3).close()
            survivors = [n for n in c.ring_nodes if n.rank != 3]
            assert wait_for(
                lambda: all(not n.view.contains(3) for n in survivors), timeout=20
            ), "rank 3 never declared dead everywhere"
            # Rank 4 is group 1's new leader and must bridge.
            assert wait_for(lambda: c.node(4)._spine_rank == 6, timeout=10)
            # Writes from the shrunken group still reach the other groups…
            insert_with_pool(c.node(4), [3, 1, 4])
            for n in survivors:
                assert wait_for(lambda n=n: n.match_prefix([3, 1, 4]).length == 3), (
                    f"rank {n.rank} missed the post-failover insert"
                )
            # …and writes from other groups still reach the shrunken group.
            insert_with_pool(c.node(8), [2, 7, 1])
            assert wait_for(lambda: c.node(5).match_prefix([2, 7, 1]).length == 3)
        finally:
            c.close()

    def test_whole_group_death_is_skipped_on_the_spine(self):
        c = HierCluster(failure_timeout_s=0.6)
        try:
            c.wait_ready()
            for r in (3, 4, 5):  # kill all of group 1
                c.node(r).close()
            survivors = [n for n in c.ring_nodes if n.rank not in (3, 4, 5)]
            assert wait_for(
                lambda: all(
                    not any(n.view.contains(d) for d in (3, 4, 5)) for n in survivors
                ),
                timeout=25,
            ), "group 1 never fully declared dead"
            assert wait_for(lambda: c.node(0)._spine_rank == 6, timeout=10), (
                "spine did not skip the dead group"
            )
            insert_with_pool(c.node(1), [8, 8, 8])
            for n in survivors:
                assert wait_for(lambda n=n: n.match_prefix([8, 8, 8]).length == 3)
        finally:
            c.close()


class TestHierServing:
    def test_engines_publish_across_groups_and_router_attributes(self):
        """The full serving stack composes with topology=hier: an engine
        on a group-0 prefill node and one on a group-1 decode node both
        publish; advertisements cross the spine to every replica and the
        router (fed by master fan-out) attributes both roles."""
        import jax

        from radixmesh_tpu.cache.kv_pool import PagedKVPool
        from radixmesh_tpu.engine.engine import Engine
        from radixmesh_tpu.engine.request import SamplingParams
        from radixmesh_tpu.models.llama import ModelConfig, init_params
        from radixmesh_tpu.router.cache_aware_router import CacheAwareRouter

        prefill = ["sp0", "sp1", "sp2", "sp3"]
        decode = ["sd0", "sd1"]
        router = ["sr0"]
        cfg = ModelConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        meshes, engines = [], {}
        page = 4
        for addr in prefill + decode + router:
            mcfg = MeshConfig(
                prefill_nodes=prefill,
                decode_nodes=decode,
                router_nodes=router,
                local_addr=addr,
                protocol="inproc",
                topology="hier",
                group_size=3,  # groups {0,1,2} and {3,4,5}
                tick_interval_s=0.05,
                gc_interval_s=30.0,
            )
            mesh = MeshCache(mcfg, pool=None).start()
            meshes.append(mesh)
            if addr in ("sp0", "sd1"):  # one engine per group
                pool = PagedKVPool(
                    num_slots=512, num_layers=cfg.n_layers,
                    num_kv_heads=cfg.n_kv_heads, head_dim=cfg.head_dim,
                    page_size=page, dtype=cfg.dtype,
                )
                engines[addr] = Engine(
                    cfg, params, pool=pool, page_size=page, max_batch=2,
                    mesh=mesh, name=addr,
                )
        try:
            for m in meshes:
                assert m.wait_ready(timeout=10), f"rank {m.rank} never ready"
            router_mesh = next(m for m in meshes if m.role is NodeRole.ROUTER)
            car = CacheAwareRouter(router_mesh, router_mesh.cfg)
            car.finish_warm_up()
            greedy = SamplingParams(temperature=0.0, max_new_tokens=3)

            prompt_a = list(range(40, 52))  # served by sp0 (rank 0, group 0)
            engines["sp0"].generate([prompt_a], greedy)
            prompt_b = list(range(60, 72))  # served by sd1 (rank 5, group 1)
            engines["sd1"].generate([prompt_b], greedy)

            # Advertisements cross the spine to a non-engine replica in
            # the OTHER group (rank 3 is group 1's leader).
            assert wait_for(
                lambda: meshes[3].match_prefix(prompt_a).length >= page
            ), "group-1 replica never saw group-0's advertisement"
            assert wait_for(
                lambda: meshes[1].match_prefix(prompt_b).length >= page
            ), "group-0 replica never saw group-1's advertisement"

            # Router attribution for both roles, across groups.
            def routed_a():
                r = car.cache_aware_route(prompt_a)
                return r.prefill_addr == "sp0"

            def routed_b():
                r = car.cache_aware_route(prompt_b)
                return r.decode_addr == "sd1"

            assert wait_for(routed_a), car.cache_aware_route(prompt_a)
            assert wait_for(routed_b), car.cache_aware_route(prompt_b)
        finally:
            for m in meshes:
                m.close()


class TestHierConfig:
    def test_ring_mode_rejects_group_size(self):
        with pytest.raises(ValueError, match="group_size"):
            MeshConfig(
                prefill_nodes=["a"], local_addr="a", group_size=4
            ).validate()

    def test_auto_group_size_applied(self):
        cfg = MeshConfig(
            prefill_nodes=[f"n{i}" for i in range(9)],
            local_addr="n0",
            protocol="inproc",
            topology="hier",
        )
        m = MeshCache(cfg)
        assert m.hier is not None and m.hier.group_size == 3
