"""Parallelism tests on the virtual 8-device CPU mesh (conftest forces
``xla_force_host_platform_device_count=8``), mirroring the reference's
multi-node-without-a-cluster strategy (SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from radixmesh_tpu.models.llama import (
    ModelConfig,
    init_params,
    param_logical_axes,
    prefill_forward,
)
from radixmesh_tpu.parallel.sharding import (
    MeshPlan,
    batch_sharding,
    make_mesh,
    param_sharding,
    shard_params,
)
from radixmesh_tpu.parallel.train import (
    causal_lm_loss,
    make_train_state,
    make_train_step,
)


def _cfg():
    # fp32 so sharded-vs-single-device comparisons are tight
    return ModelConfig.tiny().replace(dtype=jnp.float32)


class TestMeshPlan:
    def test_auto_factorizations(self):
        assert MeshPlan.auto(8) == MeshPlan(dp=1, sp=2, tp=4)
        assert MeshPlan.auto(4) == MeshPlan(dp=1, sp=1, tp=4)
        assert MeshPlan.auto(2) == MeshPlan(dp=1, sp=1, tp=2)
        assert MeshPlan.auto(1) == MeshPlan(dp=1, sp=1, tp=1)
        assert MeshPlan.auto(16) == MeshPlan(dp=2, sp=2, tp=4)

    def test_make_mesh_shape(self):
        mesh = make_mesh(MeshPlan(dp=2, sp=2, tp=2))
        assert dict(mesh.shape) == {"dp": 2, "sp": 2, "tp": 2}

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError):
            make_mesh(MeshPlan(dp=4, sp=2, tp=4))


class TestParamSharding:
    def test_tp_shards_heads_and_ffn(self):
        cfg = _cfg()
        mesh = make_mesh(MeshPlan(dp=1, sp=1, tp=2))
        params = init_params(cfg, jax.random.PRNGKey(0))
        sharded = shard_params(params, param_logical_axes(cfg), mesh)
        # wq [L, H, qd]: qd axis split over tp=2
        wq_shards = sharded["layers"]["wq"].addressable_shards
        qd = cfg.n_heads * cfg.head_dim
        assert {s.data.shape[-1] for s in wq_shards} == {qd // 2}
        # norms replicated
        norm_shards = sharded["layers"]["attn_norm"].addressable_shards
        assert all(s.data.shape == (cfg.n_layers, cfg.hidden) for s in norm_shards)

    def test_sharded_forward_matches_single_device(self):
        cfg = _cfg()
        mesh = make_mesh(MeshPlan(dp=2, sp=2, tp=2))
        params = init_params(cfg, jax.random.PRNGKey(0))
        b, s = 4, 16
        rng = np.random.default_rng(1)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (b, s)), jnp.int32)
        positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s)).astype(jnp.int32)
        ck = jnp.zeros((cfg.n_layers, b, 0, cfg.n_kv_heads, cfg.head_dim), cfg.dtype)
        plen = jnp.zeros((b,), jnp.int32)

        ref, _, _ = prefill_forward(params, cfg, tokens, positions, ck, ck, plen)

        sharded = shard_params(params, param_logical_axes(cfg), mesh)
        tok_sharded = jax.device_put(tokens, batch_sharding(mesh))
        out, _, _ = prefill_forward(sharded, cfg, tok_sharded, positions, ck, ck, plen)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


class TestTrainStep:
    def test_loss_decreases_and_matches_unsharded(self):
        cfg = _cfg()
        mesh = make_mesh(MeshPlan(dp=2, sp=2, tp=2))
        opt = optax.adamw(1e-2)
        state = make_train_state(cfg, jax.random.PRNGKey(0), mesh, opt)
        step = make_train_step(cfg, mesh, opt)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 17)), jnp.int32)

        # unsharded oracle for the first loss value
        params0 = init_params(cfg, jax.random.PRNGKey(0))
        ref_loss = float(causal_lm_loss(params0, cfg, tokens))

        losses = []
        for _ in range(5):
            state, loss = step(state, tokens)
            losses.append(float(loss))
        assert abs(losses[0] - ref_loss) < 1e-3
        assert losses[-1] < losses[0]
        assert int(state.step) == 5

    def test_opt_state_sharded_like_params(self):
        cfg = _cfg()
        mesh = make_mesh(MeshPlan(dp=1, sp=1, tp=2))
        opt = optax.adamw(1e-3)
        state = make_train_state(cfg, jax.random.PRNGKey(0), mesh, opt)
        mu_wq = state.opt_state[0].mu["layers"]["wq"]
        qd = cfg.n_heads * cfg.head_dim
        assert {s.data.shape[-1] for s in mu_wq.addressable_shards} == {qd // 2}


class TestGraftEntry:
    def test_entry_compiles(self):
        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.jit(fn)(*args)
        assert out.shape[0] == args[1].shape[0]
        assert bool(jnp.isfinite(out).all())

    def test_dryrun_multichip(self):
        import __graft_entry__ as g

        g.dryrun_multichip(8)
