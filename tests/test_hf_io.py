"""Golden HF-checkpoint tests (VERDICT round-2 next-step #4).

The north star serves Llama-3-8B from its HF checkpoint
(``BASELINE.json`` "north_star"); until this file, ``convert_hf_state_dict``
had never met HF-formatted bytes. Three layers of proof:

- safetensors shard/index round-trip is bitwise lossless (``models/hf_io.py``)
- a REAL ``transformers`` Llama/Qwen2 model saved with ``save_pretrained``
  loads through ``load_hf_checkpoint`` and our forward matches the HF
  torch forward's logits (fp32)
- greedy generation through our Engine is token-exact vs HF ``generate``
  — serving parity, not just one forward
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from radixmesh_tpu.models.hf_io import (  # noqa: E402
    load_hf_checkpoint,
    load_hf_state_dict,
    save_hf_state_dict,
)
from radixmesh_tpu.models.llama import ModelConfig, prefill_forward  # noqa: E402

_TINY_DIMS = dict(
    vocab_size=512,
    hidden=128,
    n_layers=2,
    n_heads=4,
    n_kv_heads=2,
    head_dim=32,
    intermediate=256,
    rope_theta=10000.0,
    rope_scaling=None,
    max_seq_len=512,
    dtype=jnp.float32,  # fp32 end to end: parity must not hide in bf16 noise
)


def _hf_llama(tmp_path, qkv_bias: bool):
    """Build + save a REAL transformers checkpoint; return (model, dir)."""
    torch = pytest.importorskip("torch")
    if qkv_bias:
        from transformers import Qwen2Config, Qwen2ForCausalLM as Model

        hf_cfg = Qwen2Config(
            vocab_size=512, hidden_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=256, rope_theta=10000.0,
            rms_norm_eps=1e-5, max_position_embeddings=512,
            tie_word_embeddings=False, use_cache=False,
        )
    else:
        from transformers import LlamaConfig, LlamaForCausalLM as Model

        hf_cfg = LlamaConfig(
            vocab_size=512, hidden_size=128, num_hidden_layers=2,
            num_attention_heads=4, num_key_value_heads=2,
            intermediate_size=256, rope_theta=10000.0,
            rms_norm_eps=1e-5, max_position_embeddings=512,
            tie_word_embeddings=False, attention_bias=False,
            use_cache=False,
        )
    torch.manual_seed(7)
    model = Model(hf_cfg).to(torch.float32).eval()
    ckpt = tmp_path / ("qwen2" if qkv_bias else "llama")
    model.save_pretrained(ckpt, safe_serialization=True)
    return model, str(ckpt)


def _our_logits(cfg, params, ids: list[int]) -> np.ndarray:
    toks = jnp.asarray([ids], jnp.int32)
    pos = jnp.arange(len(ids), dtype=jnp.int32)[None, :]
    L, B = cfg.n_layers, 1
    empty = jnp.zeros((L, B, 0, cfg.n_kv_heads, cfg.head_dim), cfg.dtype)
    logits, _, _ = prefill_forward(
        params, cfg, toks, pos, empty, empty, jnp.zeros((B,), jnp.int32)
    )
    return np.asarray(logits[0], np.float32)


def test_shard_roundtrip_bitexact(tmp_path):
    rng = np.random.default_rng(0)
    state = {
        f"model.layers.{i}.weight_{j}": rng.normal(
            size=(64, 48)
        ).astype(np.float32)
        for i in range(4)
        for j in range(3)
    }
    # Tiny shard cap forces the index+multi-shard layout.
    save_hf_state_dict(state, str(tmp_path / "ck"), max_shard_bytes=40000)
    files = list((tmp_path / "ck").iterdir())
    assert any(f.name.endswith("index.json") for f in files)
    assert sum(f.name.endswith(".safetensors") for f in files) > 1
    back = load_hf_state_dict(str(tmp_path / "ck"))
    assert set(back) == set(state)
    for k in state:
        assert back[k].dtype == state[k].dtype
        np.testing.assert_array_equal(back[k], state[k])


@pytest.mark.parametrize("qkv_bias", [False, True], ids=["llama", "qwen2"])
def test_hf_checkpoint_logits_parity(tmp_path, qkv_bias):
    torch = pytest.importorskip("torch")
    hf_model, ckpt = _hf_llama(tmp_path, qkv_bias)
    cfg = ModelConfig(qkv_bias=qkv_bias, **_TINY_DIMS)
    params = load_hf_checkpoint(ckpt, cfg)

    ids = [3, 141, 59, 26, 250, 8, 99, 400, 77, 12]
    ours = _our_logits(cfg, params, ids)
    with torch.no_grad():
        theirs = (
            hf_model(torch.tensor([ids])).logits[0].float().numpy()
        )
    np.testing.assert_allclose(ours, theirs, rtol=2e-4, atol=2e-4)


def test_hf_checkpoint_greedy_generation_parity(tmp_path):
    torch = pytest.importorskip("torch")
    from radixmesh_tpu.engine.engine import Engine
    from radixmesh_tpu.engine.request import SamplingParams

    hf_model, ckpt = _hf_llama(tmp_path, qkv_bias=False)
    cfg = ModelConfig(**_TINY_DIMS)
    params = load_hf_checkpoint(ckpt, cfg)

    prompt = [3, 141, 59, 26, 250, 8, 99, 400]
    n_new = 8
    with torch.no_grad():
        hf_out = hf_model.generate(
            torch.tensor([prompt]), max_new_tokens=n_new, do_sample=False,
            use_cache=True,
        )[0, len(prompt):].tolist()

    engine = Engine(cfg, params, num_slots=1024, page_size=16, max_batch=2)
    ours = engine.generate(
        [prompt], SamplingParams(temperature=0.0, max_new_tokens=n_new)
    )[0]
    assert ours == hf_out, (
        f"greedy generation diverged: ours={ours} hf={hf_out}"
    )
