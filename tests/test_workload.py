"""North-star workload: multi-turn prefix sharing through the Engine.

Validates the BASELINE.json "north_star" measurement machinery at tiny
scale: the synthetic ShareGPT-shaped workload must actually produce high
prefix-cache hit-rates (turn k reuses turn k-1's full context), and the
report must be deterministic in the workload seed.
"""

import jax
import pytest

from radixmesh_tpu.engine.engine import Engine
from radixmesh_tpu.models.llama import ModelConfig, init_params
from radixmesh_tpu.workload import MultiTurnWorkload, run_engine_workload


@pytest.fixture(scope="module")
def engine_factory():
    cfg = ModelConfig.tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))

    def make():
        return Engine(cfg, params, num_slots=4096, page_size=4, max_batch=4)

    return make


def test_workload_shape_determinism():
    a = MultiTurnWorkload(n_conversations=3, n_turns=2, seed=7)
    b = MultiTurnWorkload(n_conversations=3, n_turns=2, seed=7)
    assert a.system == b.system
    assert a.round_prompts(0)[2][1] == b.round_prompts(0)[2][1]
    c = MultiTurnWorkload(n_conversations=3, n_turns=2, seed=8)
    assert a.system != c.system


def test_multi_turn_hit_rate_meets_target(engine_factory):
    """With 4 turns the within-conversation reuse alone must clear the 70%
    north-star target (each turn's prompt embeds the whole prior context)."""
    engine = engine_factory()
    wl = MultiTurnWorkload(
        n_conversations=4, n_turns=4, system_len=32, user_len=16,
        gen_len=8, vocab_size=512, seed=0,
    )
    report = run_engine_workload(engine, wl)
    assert report["requests"] == 16
    assert report["prompt_tokens"] > 0
    assert report["hit_rate"] >= 0.70, report
    assert report["p50_ttft_s"] > 0
    # Engine-side counters agree with the report's arithmetic.
    assert report["cached_tokens"] <= report["prompt_tokens"]


def test_first_turns_are_cold(engine_factory):
    """A single-turn workload on a fresh engine is almost all cold: only
    cross-conversation system-prefix reuse (bounded by page alignment)."""
    engine = engine_factory()
    wl = MultiTurnWorkload(
        n_conversations=4, n_turns=1, system_len=32, user_len=16,
        gen_len=8, vocab_size=512, seed=0,
    )
    report = run_engine_workload(engine, wl)
    # At most the 32-token system prefix per request can ever hit.
    assert report["hit_rate"] <= 32 / (32 + 16)


def test_ceiling_and_efficiency(engine_factory):
    """The ceiling is what an infinite cache could reuse: measured hit
    rate can't (meaningfully) exceed it, and a warm multi-turn run should
    capture most of it."""
    engine = engine_factory()
    wl = MultiTurnWorkload(
        n_conversations=4, n_turns=4, system_len=32, user_len=16,
        gen_len=8, vocab_size=512, seed=0,
    )
    report = run_engine_workload(engine, wl)
    assert 0.0 < report["ceiling_hit_rate"] <= 1.0
    assert report["hit_rate"] <= report["ceiling_hit_rate"] + 0.02
    assert report["reuse_efficiency"] >= 0.85, report
