"""Dedicated tests for ``router/consistent_hash.py`` (it carried zero
before sharding made it load-bearing): virtual-node distribution
balance, add/remove stability (bounded key movement — the property
consistent hashing exists for), and the RF-successor walk
(``get_nodes``) that prefix-ownership sharding derives owner sets from
(distinct owners, wrap-around, N < RF degeneracy, exclusion)."""

import pytest

from radixmesh_tpu.router.consistent_hash import ConsistentHash

pytestmark = pytest.mark.quick


def _keys(n: int):
    return [f"key-{i}" for i in range(n)]


class TestDistribution:
    def test_balance_across_virtual_nodes(self):
        """With enough virtual nodes, no node owns a wildly outsized
        share of a large key population (generous 4x bound — 32-bit
        blake2b points are not a perfect partition, but an unbalanced
        ring defeats the whole fallback-spread purpose)."""
        nodes = [f"n{i}" for i in range(8)]
        ring = ConsistentHash(nodes, virtual_nodes=32)
        counts = {n: 0 for n in nodes}
        for k in _keys(4000):
            counts[ring.get_node(k)] += 1
        expected = 4000 / len(nodes)
        assert max(counts.values()) < 4 * expected
        assert min(counts.values()) > expected / 4

    def test_more_virtual_nodes_participate(self):
        """Every node actually lands points on the ring (a node with no
        points would silently take zero traffic)."""
        nodes = [f"n{i}" for i in range(16)]
        ring = ConsistentHash(nodes, virtual_nodes=8)
        owners = {ring.get_node(k) for k in _keys(2000)}
        assert owners == set(nodes)


class TestStability:
    def test_add_node_moves_bounded_keys(self):
        """Adding one node to a 10-node ring re-maps roughly 1/11 of
        keys (3x slack for point-placement variance) — never a full
        reshuffle."""
        nodes = [f"n{i}" for i in range(10)]
        before = ConsistentHash(nodes, virtual_nodes=32)
        after = ConsistentHash(nodes + ["n10"], virtual_nodes=32)
        keys = _keys(3000)
        moved = sum(
            1 for k in keys if before.get_node(k) != after.get_node(k)
        )
        assert moved / len(keys) < 3.0 / 11.0
        # Every moved key moved TO the new node (the defining property:
        # existing nodes never trade keys among themselves on an add).
        for k in keys:
            if before.get_node(k) != after.get_node(k):
                assert after.get_node(k) == "n10"

    def test_remove_node_only_reassigns_its_keys(self):
        nodes = [f"n{i}" for i in range(10)]
        ring = ConsistentHash(nodes, virtual_nodes=32)
        keys = _keys(3000)
        before = {k: ring.get_node(k) for k in keys}
        ring.remove_node("n3")
        for k in keys:
            if before[k] != "n3":
                assert ring.get_node(k) == before[k]
            else:
                assert ring.get_node(k) != "n3"

    def test_incremental_equals_rebuilt(self):
        """Mutating a ring in place converges to the same assignment as
        building it fresh (the router mutates on view changes)."""
        a = ConsistentHash(["x", "y", "z"], virtual_nodes=16)
        a.remove_node("y")
        a.add_node("w")
        b = ConsistentHash(["x", "z", "w"], virtual_nodes=16)
        for k in _keys(500):
            assert a.get_node(k) == b.get_node(k)


class TestRFSuccessorWalk:
    def test_distinct_owners(self):
        ring = ConsistentHash([f"n{i}" for i in range(12)], virtual_nodes=8)
        for k in _keys(200):
            owners = ring.get_nodes(k, 3)
            assert len(owners) == 3
            assert len(set(owners)) == 3

    def test_first_owner_matches_get_node(self):
        """The walk's head is the natural single owner — sharding's
        primary == the routing fallback's answer."""
        ring = ConsistentHash([f"n{i}" for i in range(9)], virtual_nodes=8)
        for k in _keys(200):
            assert ring.get_nodes(k, 3)[0] == ring.get_node(k)

    def test_wraparound_collects_all(self):
        """A walk starting near the top of the hash space wraps to the
        ring's start: asking for every node always returns every node,
        wherever the key hashes."""
        nodes = [f"n{i}" for i in range(5)]
        ring = ConsistentHash(nodes, virtual_nodes=4)
        for k in _keys(300):
            assert set(ring.get_nodes(k, 5)) == set(nodes)

    def test_n_below_rf_degeneracy(self):
        """Fewer nodes than the requested factor: the walk returns every
        distinct node (sharding's full-replica degeneracy) instead of
        padding or raising."""
        ring = ConsistentHash(["a", "b"], virtual_nodes=8)
        owners = ring.get_nodes("some-key", 3)
        assert sorted(owners) == ["a", "b"]
        assert ConsistentHash([]).get_nodes("k", 3) == []

    def test_exclusion_and_zero(self):
        ring = ConsistentHash(["a", "b", "c"], virtual_nodes=8)
        assert ring.get_nodes("k", 0) == []
        owners = ring.get_nodes("k", 3, exclude={"b"})
        assert "b" not in owners and len(owners) == 2

    def test_deterministic_across_instances(self):
        """Two independently built rings over the same membership agree
        on every walk — the zero-coordination property ownership maps
        (cache/sharding.py) are derived from."""
        nodes = [f"rank:{i}" for i in range(20)]
        r1 = ConsistentHash(nodes, virtual_nodes=8)
        r2 = ConsistentHash(reversed(nodes), virtual_nodes=8)
        for k in _keys(300):
            assert r1.get_nodes(k, 3) == r2.get_nodes(k, 3)
