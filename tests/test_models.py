"""Model-family tests (tiny configs, fp32, CPU).

The golden test is cache-path equivalence: decode over paged radix-cache
KV must reproduce dense full-prefill logits, and prefill-with-cached-prefix
must reproduce full prefill — the exactness properties that make radix
prefix reuse sound end-to-end.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from radixmesh_tpu.models import get_config
from radixmesh_tpu.models.llama import (
    convert_hf_state_dict,
    decode_step,
    init_params,
    param_logical_axes,
    prefill_forward,
)

PAGE = 4


def tiny(**kw):
    return get_config("llama3-tiny", dtype=jnp.float32, **kw)


def full_prefill(params, cfg, tokens):
    B, S = tokens.shape
    L = cfg.n_layers
    no_cache = jnp.zeros((L, B, 0, cfg.n_kv_heads, cfg.head_dim), dtype=jnp.float32)
    logits, new_k, new_v = prefill_forward(
        params,
        cfg,
        tokens,
        jnp.arange(S)[None, :].repeat(B, 0),
        no_cache,
        no_cache,
        jnp.zeros((B,), dtype=jnp.int32),
    )
    return logits, new_k, new_v


@pytest.fixture(scope="module")
def setup():
    cfg = tiny()
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 13), 0, cfg.vocab_size)
    return cfg, params, tokens


class TestPrefill:
    def test_shapes(self, setup):
        cfg, params, tokens = setup
        logits, new_k, new_v = full_prefill(params, cfg, tokens)
        assert logits.shape == (1, 13, cfg.vocab_size)
        assert new_k.shape == (cfg.n_layers, 1, 13, cfg.n_kv_heads, cfg.head_dim)

    def test_cached_prefix_matches_full_prefill(self, setup):
        cfg, params, tokens = setup
        n_prefix = 8
        full_logits, new_k, new_v = full_prefill(params, cfg, tokens)
        # Continue from a cached prefix: K/V of the first 8 tokens.
        ck, cv = new_k[:, :, :n_prefix], new_v[:, :, :n_prefix]
        cont_logits, _, _ = prefill_forward(
            params,
            cfg,
            tokens[:, n_prefix:],
            jnp.arange(n_prefix, 13)[None, :],
            ck,
            cv,
            jnp.array([n_prefix], dtype=jnp.int32),
        )
        np.testing.assert_allclose(
            np.asarray(cont_logits),
            np.asarray(full_logits[:, n_prefix:]),
            rtol=2e-4,
            atol=2e-4,
        )

    def test_ragged_right_aligned_prefix(self, setup):
        # Prefix region padded at the FRONT (P_max > prefix_len) must give
        # identical logits — the batched ragged-hit case.
        cfg, params, tokens = setup
        n_prefix, p_max = 8, 12
        full_logits, new_k, new_v = full_prefill(params, cfg, tokens)
        pad = p_max - n_prefix
        ck = jnp.pad(
            new_k[:, :, :n_prefix], ((0, 0), (0, 0), (pad, 0), (0, 0), (0, 0))
        )
        cv = jnp.pad(
            new_v[:, :, :n_prefix], ((0, 0), (0, 0), (pad, 0), (0, 0), (0, 0))
        )
        cont_logits, _, _ = prefill_forward(
            params,
            cfg,
            tokens[:, n_prefix:],
            jnp.arange(n_prefix, 13)[None, :],
            ck,
            cv,
            jnp.array([n_prefix], dtype=jnp.int32),
        )
        np.testing.assert_allclose(
            np.asarray(cont_logits),
            np.asarray(full_logits[:, n_prefix:]),
            rtol=2e-4,
            atol=2e-4,
        )


class TestDecode:
    def test_paged_decode_matches_prefill_logits(self, setup):
        """Prefill S tokens, write KV to a paged pool, decode token S+1 —
        logits must equal dense prefill of S+1 tokens."""
        cfg, params, _ = setup
        S = 12  # multiple of PAGE
        tokens = jax.random.randint(jax.random.PRNGKey(3), (1, S + 1), 0, cfg.vocab_size)
        full_logits, new_k, new_v = full_prefill(params, cfg, tokens)

        # Paged pool holding the first S tokens' KV at slots 0..S-1.
        num_slots = 32
        kv_pool = jnp.zeros(
            (2, cfg.n_layers, cfg.n_kv_heads, num_slots, cfg.head_dim),
            dtype=jnp.float32,
        )
        # new_k: [L, B, S, Hkv, D] → head-major [L, Hkv, S, D].
        k_hm = new_k[:, 0, :S].transpose(0, 2, 1, 3)
        v_hm = new_v[:, 0, :S].transpose(0, 2, 1, 3)
        kv_pool = kv_pool.at[0, :, :, :S].set(k_hm)
        kv_pool = kv_pool.at[1, :, :, :S].set(v_hm)

        max_pages = num_slots // PAGE
        page_table = jnp.arange(max_pages, dtype=jnp.int32)[None, :]
        logits, kv_pool = decode_step(
            params,
            cfg,
            tokens[:, S],
            kv_pool,
            jnp.array([S], dtype=jnp.int32),
            page_table,
            jnp.array([S + 1], dtype=jnp.int32),
            page_size=PAGE,
        )
        np.testing.assert_allclose(
            np.asarray(logits),
            np.asarray(full_logits[:, S]),
            rtol=2e-4,
            atol=2e-4,
        )

    def test_multi_step_decode_matches_prefill(self, setup):
        """Three successive decode steps reproduce the dense logits."""
        cfg, params, _ = setup
        S = 8
        T = 3
        tokens = jax.random.randint(
            jax.random.PRNGKey(4), (1, S + T), 0, cfg.vocab_size
        )
        full_logits, new_k, new_v = full_prefill(params, cfg, tokens)
        num_slots = 16
        kv_pool = jnp.zeros(
            (2, cfg.n_layers, cfg.n_kv_heads, num_slots, cfg.head_dim),
            dtype=jnp.float32,
        )
        kv_pool = kv_pool.at[0, :, :, :S].set(new_k[:, 0, :S].transpose(0, 2, 1, 3))
        kv_pool = kv_pool.at[1, :, :, :S].set(new_v[:, 0, :S].transpose(0, 2, 1, 3))
        page_table = jnp.arange(num_slots // PAGE, dtype=jnp.int32)[None, :]
        for t in range(T):
            logits, kv_pool = decode_step(
                params,
                cfg,
                tokens[:, S + t],
                kv_pool,
                jnp.array([S + t], dtype=jnp.int32),
                page_table,
                jnp.array([S + t + 1], dtype=jnp.int32),
                page_size=PAGE,
            )
            np.testing.assert_allclose(
                np.asarray(logits),
                np.asarray(full_logits[:, S + t]),
                rtol=3e-4,
                atol=3e-4,
            )


class TestQwen2:
    def test_bias_params_exist_and_forward(self):
        cfg = get_config("qwen2-tiny", dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        assert "bq" in params["layers"]
        tokens = jnp.array([[1, 2, 3]])
        logits, _, _ = full_prefill(params, cfg, tokens)
        assert logits.shape == (1, 3, cfg.vocab_size)
        # Bias actually participates.
        params2 = dict(params)
        params2["layers"] = dict(params["layers"])
        params2["layers"]["bq"] = params["layers"]["bq"] + 1.0
        logits2, _, _ = full_prefill(params2, cfg, tokens)
        assert not np.allclose(np.asarray(logits), np.asarray(logits2))


class TestHFConversion:
    def test_roundtrip_against_init_shapes(self):
        cfg = tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        # Build a synthetic HF state dict with matching shapes.
        state = {
            "model.embed_tokens.weight": np.asarray(params["embed"]),
            "model.norm.weight": np.asarray(params["final_norm"]),
            "lm_head.weight": np.asarray(params["lm_head"]).T,
        }
        hf_names = {
            "wq": "self_attn.q_proj",
            "wk": "self_attn.k_proj",
            "wv": "self_attn.v_proj",
            "wo": "self_attn.o_proj",
            "w_gate": "mlp.gate_proj",
            "w_up": "mlp.up_proj",
            "w_down": "mlp.down_proj",
        }
        for i in range(cfg.n_layers):
            state[f"model.layers.{i}.input_layernorm.weight"] = np.asarray(
                params["layers"]["attn_norm"][i]
            )
            state[f"model.layers.{i}.post_attention_layernorm.weight"] = np.asarray(
                params["layers"]["mlp_norm"][i]
            )
            for ours, theirs in hf_names.items():
                state[f"model.layers.{i}.{theirs}.weight"] = np.asarray(
                    params["layers"][ours][i]
                ).T
        converted = convert_hf_state_dict(cfg, state)
        # Converted params must produce identical logits.
        tokens = jnp.array([[5, 6, 7]])
        a, _, _ = full_prefill(params, cfg, tokens)
        b, _, _ = full_prefill(converted, cfg, tokens)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)

    def test_logical_axes_cover_every_param(self):
        for name in ("llama3-tiny", "qwen2-tiny"):
            cfg = get_config(name, dtype=jnp.float32)
            params = init_params(cfg, jax.random.PRNGKey(0))
            axes = param_logical_axes(cfg)
            flat_p = jax.tree_util.tree_leaves_with_path(params)
            flat_a = dict(
                (jax.tree_util.keystr(k), v)
                for k, v in jax.tree_util.tree_leaves_with_path(
                    axes, is_leaf=lambda x: isinstance(x, tuple)
                )
            )
            for path, leaf in flat_p:
                key = jax.tree_util.keystr(path)
                assert key in flat_a, f"no logical axes for {key}"
                assert len(flat_a[key]) == leaf.ndim, f"rank mismatch for {key}"


class TestDecodeMultiCompact:
    """``decode_multi_compact`` (the kernel-less-backend decode path:
    one pool gather + one scatter-back per launch instead of k·L
    pool-sized scatter copies) must be TOKEN-EXACT with ``decode_multi``
    and leave the full pool identical on every real slot."""

    def _setup(self, quant=False):
        from radixmesh_tpu.models.llama import ModelConfig, init_params

        cfg = ModelConfig.tiny().replace(dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(0))
        B, ps, maxp, k = 4, 4, 8, 3
        # A LARGE pool (many more pages than the working set) so the
        # compact gather actually exercises the indirection.
        num_slots = 512 * ps
        rng = np.random.default_rng(23)
        if quant:
            pool = rng.integers(
                -127, 128,
                (2, cfg.n_layers, cfg.n_kv_heads, num_slots, cfg.head_dim),
            ).astype(np.int8)
            scale = (np.abs(rng.normal(
                size=(2, cfg.n_layers, cfg.n_kv_heads, num_slots)
            )) * 0.01).astype(np.float32)
        else:
            pool = np.asarray(rng.normal(
                size=(2, cfg.n_layers, cfg.n_kv_heads, num_slots,
                      cfg.head_dim)
            ), np.float32)
            scale = None
        # Scattered, non-contiguous pages per row (the radix allocator's
        # steady state) + a scratch page.
        all_pages = rng.permutation(512)[: B * maxp + 1].astype(np.int32)
        pt = all_pages[: B * maxp].reshape(B, maxp)
        scratch_page = int(all_pages[-1])
        lengths = np.asarray([3, 9, 14, 6], np.int32)
        tokens = rng.integers(1, cfg.vocab_size, B).astype(np.int32)
        return (cfg, params, pool, scale, pt, scratch_page, lengths,
                tokens, ps, k)

    @pytest.mark.parametrize("quant", [False, True])
    def test_matches_decode_multi(self, quant):
        from radixmesh_tpu.models.llama import (
            decode_multi,
            decode_multi_compact,
        )

        (cfg, params, pool, scale, pt, scratch_page, lengths, tokens,
         ps, k) = self._setup(quant)
        B, maxp = pt.shape
        zeros = jnp.zeros((B,), jnp.float32)
        ones = jnp.ones((B,), jnp.float32)
        topk0 = jnp.zeros((B,), jnp.int32)
        key = jax.random.PRNGKey(31)
        kw = dict(page_size=ps, k_steps=k, top_ks=topk0)
        if quant:
            res_full = decode_multi(
                params, cfg, jnp.asarray(tokens), jnp.asarray(pool),
                jnp.asarray(pt), jnp.asarray(lengths), key, zeros, ones,
                kv_scale=jnp.asarray(scale), **kw,
            )
        else:
            res_full = decode_multi(
                params, cfg, jnp.asarray(tokens), jnp.asarray(pool),
                jnp.asarray(pt), jnp.asarray(lengths), key, zeros, ones,
                **kw,
            )

        # Compact mapping exactly as the engine builds it.
        uniq = np.unique(np.concatenate(
            [pt.reshape(-1), [scratch_page]]
        )).astype(np.int32)
        n_c = 1 << (len(uniq) - 1).bit_length()
        compact = np.full(n_c, scratch_page, dtype=np.int32)
        compact[: len(uniq)] = uniq
        pt_c = np.searchsorted(uniq, pt).astype(np.int32)
        if quant:
            res_c = decode_multi_compact(
                params, cfg, jnp.asarray(tokens), jnp.asarray(pool),
                jnp.asarray(compact), jnp.asarray(pt_c),
                jnp.asarray(lengths), key, zeros, ones,
                kv_scale=jnp.asarray(scale), **kw,
            )
        else:
            res_c = decode_multi_compact(
                params, cfg, jnp.asarray(tokens), jnp.asarray(pool),
                jnp.asarray(compact), jnp.asarray(pt_c),
                jnp.asarray(lengths), key, zeros, ones, **kw,
            )
        np.testing.assert_array_equal(
            np.asarray(res_c[0]), np.asarray(res_full[0])
        )
        # Full pool identical everywhere EXCEPT the scratch page (the
        # compact path's padding may rewrite it; contents are dead).
        live = np.ones(np.asarray(res_full[1]).shape[3], bool)
        live[scratch_page * ps : (scratch_page + 1) * ps] = False
        np.testing.assert_allclose(
            np.asarray(res_c[1])[:, :, :, live],
            np.asarray(res_full[1])[:, :, :, live],
            rtol=1e-6, atol=1e-6,
        )
        if quant:
            np.testing.assert_allclose(
                np.asarray(res_c[2])[:, :, :, live],
                np.asarray(res_full[2])[:, :, :, live],
                rtol=1e-6, atol=1e-6,
            )
