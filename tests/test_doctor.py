"""Mesh doctor rule engine (obs/doctor.py): every rule unit-tested
against synthetic FleetView/heat/histogram fixtures — each fires on its
seeded pathology with the pinned evidence fields, stays silent on the
healthy shape of the same inputs, and a broken rule degrades to a
finding instead of an outage. The burn-rate tracker runs on a virtual
clock so the 5m/1h windows are exact, not slept."""

import pytest

from radixmesh_tpu.obs.attribution import ensure_attributor
from radixmesh_tpu.obs.doctor import (
    RULE_EVIDENCE_FIELDS,
    RULES,
    BurnRateTracker,
    DoctorConfig,
    Finding,
    MeshDoctor,
)
from radixmesh_tpu.obs.metrics import Registry, set_registry
from radixmesh_tpu.obs.trace_plane import FlightRecorder

pytestmark = pytest.mark.quick


@pytest.fixture(autouse=True)
def fresh_registry():
    old = set_registry(Registry())
    yield
    set_registry(old)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeMesh:
    """MeshCache stand-in: sharded flag + heat report + fleet digests."""

    def __init__(self, sharded=True, skew=1.0, hot_shard=7,
                 hot_owners=(0, 1, 2), reporters=4, lags=None):
        self.sharded = sharded
        self._report = {
            "skew_score": skew,
            "hot_shard": hot_shard,
            "hot_owners": list(hot_owners),
            "reporters": reporters,
            "shards": {},
        }
        self.fleet = self
        self._lags = dict(lags or {})

    def shard_heat_report(self):
        return dict(self._report)

    def digests(self):
        class D:
            def __init__(self, lag):
                self.replication_lag_s = lag

        return {rank: D(lag) for rank, lag in self._lags.items()}

    def shard_heat(self):
        # The FleetView heat-map surface (fleet = self): the
        # rebalancer_asleep rule's skew trajectory source.
        return dict(self._report, by_rank={})


class FakeRebalancePlane:
    def __init__(self, moves: int = 0):
        self.moves = moves

    def moves_in_window(self, window_s: float) -> int:
        return self.moves


class FakeKVPlane:
    def __init__(self, queued=0, staged=0):
        self._s = {"restores_queued": queued, "staged_chunks": staged}

    def stats(self):
        return dict(self._s)


class FakeEngine:
    def __init__(self, parked=0, queued=0, staged=0, spec=None):
        self._restoring = [(None, None)] * parked
        self.kv_transfer = FakeKVPlane(queued, staged)
        self._spec = spec or {}

    def spec_report(self):
        return {
            shape: {
                "proposed": p,
                "accepted": a,
                "acceptance": round(a / p, 4) if p else 0.0,
            }
            for shape, (p, a) in self._spec.items()
        }


class FakeSLO:
    def __init__(self):
        self.counts = {}
        self.tier = 0

    def burn_counts(self):
        return {t: dict(c) for t, c in self.counts.items()}


def _attr_with_shapes(shapes):
    """An attributor whose by_shape table is fed synthetically:
    shapes = {label: (count, e2e_each, {phase: seconds_each})}."""
    rec = FlightRecorder(capacity=1024, sample=1.0, node="fx")
    attr = ensure_attributor(rec)
    from radixmesh_tpu.obs.attribution import PHASES, Waterfall

    tid = 1
    for shape, (count, e2e, phases) in shapes.items():
        for _ in range(count):
            full = {p: 0.0 for p in PHASES}
            full.update(phases)
            full["edge"] = max(0.0, e2e - sum(phases.values()))
            wf = Waterfall(
                trace_id=tid, t0=0.0, e2e_s=e2e, phases=full,
                retire="request_done", shape=shape,
            )
            attr._feed_locked(wf)
            tid += 1
    return attr


class TestBurnRateTracker:
    def test_burn_multiple_over_window(self):
        clk = FakeClock()
        bt = BurnRateTracker(budget=0.01, now=clk)
        bt.sample({"t0": {"admitted": 0, "shed": 0}})
        for _ in range(60):
            clk.advance(5.0)
            bt.sample({"t0": {"admitted": 80, "shed": 20}})
        burn, offered = bt.burn("t0", 300.0)
        # 20% shed against a 1% budget = 20x burn.
        assert burn == pytest.approx(20.0)
        assert offered == 100

    def test_zero_offered_is_zero_burn(self):
        clk = FakeClock()
        bt = BurnRateTracker(budget=0.01, now=clk)
        bt.sample({"t0": {"admitted": 5, "shed": 0}})
        clk.advance(10)
        bt.sample({"t0": {"admitted": 5, "shed": 0}})
        assert bt.burn("t0", 300.0) == (0.0, 0)

    def test_window_diffs_against_oldest_inside_window(self):
        clk = FakeClock()
        bt = BurnRateTracker(budget=0.1, now=clk)
        bt.sample({"t0": {"admitted": 0, "shed": 0}})
        clk.advance(10)
        bt.sample({"t0": {"admitted": 0, "shed": 100}})  # old storm
        clk.advance(4000)
        bt.sample({"t0": {"admitted": 100, "shed": 100}})
        clk.advance(10)
        bt.sample({"t0": {"admitted": 200, "shed": 100}})
        # 5m window excludes the storm: zero NEW shed.
        burn_fast, _ = bt.burn("t0", 300.0)
        assert burn_fast == pytest.approx(0.0)
        # 2h window reaches back to the oldest sample: 100 shed / 300.
        burn_slow, _ = bt.burn("t0", 7200.0)
        assert burn_slow == pytest.approx((100 / 300) / 0.1)

    def test_max_samples_override_keeps_prewindow_base(self):
        # The replay path (postmortem_report) records at min_spacing 0,
        # so MAX_SAMPLES=720 — sized for the live 5 s spacing — would
        # silently evict the ring's head. max_samples= must widen the
        # ring so the "ring younger than window" branch still reaches
        # the true first sample.
        bt = BurnRateTracker(
            budget=0.01, min_spacing_s=0.0,
            max_base_lag_s=float("inf"), max_samples=1001,
        )
        a = s = 0
        t0 = 1000.0
        bt.sample({"t": {"admitted": 0, "shed": 0}}, t=t0)
        for i in range(1000):
            if i < 60:
                s += 10  # the burst is at the HEAD of the record
            else:
                a += 10
            bt.sample({"t": {"admitted": a, "shed": s}}, t=t0 + 1 + i)
        # Window wider than the record span: judged over the actual
        # span — which must include the early burst. A 720-sample ring
        # has evicted it (base would land past the burst → burn 0).
        slow, offered = bt.burn("t", 3600.0, t=t0 + 1000)
        assert offered == 600 + 9400
        assert slow == pytest.approx((600 / 10000) / 0.01)


class TestHotShardRule:
    def test_fires_with_owner_evidence(self):
        mesh = FakeMesh(skew=9.0, hot_shard=7, hot_owners=(4, 0, 2),
                        reporters=5)
        report = MeshDoctor(mesh=mesh).diagnose()
        (f,) = report["findings"]
        assert f["rule"] == "hot_shard"
        assert f["evidence"]["shard"] == 7
        assert f["evidence"]["owners"] == [0, 2, 4]  # sorted
        assert f["evidence"]["skew_score"] == 9.0
        assert f["evidence"]["reporters"] == 5

    def test_silent_below_threshold_or_unsharded(self):
        assert MeshDoctor(mesh=FakeMesh(skew=3.9)).diagnose()["findings"] == []
        assert (
            MeshDoctor(mesh=FakeMesh(sharded=False, skew=50.0))
            .diagnose()["findings"]
            == []
        )


class TestPrefillConvoyRule:
    def test_fires_on_prefill_dominant_slow_shape(self):
        attr = _attr_with_shapes({
            "p2048": (3, 1.0, {"prefill": 0.8}),
            "p128": (6, 0.1, {"decode": 0.08}),
        })
        report = MeshDoctor(attributor=attr).diagnose()
        (f,) = report["findings"]
        assert f["rule"] == "prefill_convoy"
        assert f["evidence"]["shape"] == "p2048"
        assert f["evidence"]["prefill_share"] == pytest.approx(0.8)
        assert f["evidence"]["requests"] == 3

    def test_silent_when_prefill_dominant_but_not_slower(self):
        # Batch-1-style traffic: prefill-heavy is its nature, not a
        # convoy — every shape at similar e2e stays silent.
        attr = _attr_with_shapes({
            "p2048": (3, 0.1, {"prefill": 0.08}),
            "p128": (6, 0.1, {"decode": 0.08}),
        })
        assert MeshDoctor(attributor=attr).diagnose()["findings"] == []

    def test_silent_below_min_requests(self):
        attr = _attr_with_shapes({"p2048": (2, 1.0, {"prefill": 0.9})})
        assert MeshDoctor(attributor=attr).diagnose()["findings"] == []


class TestRestoreParkRule:
    def test_fires_on_live_parked_backlog(self):
        eng = FakeEngine(parked=3, queued=2, staged=8)
        report = MeshDoctor(engine=eng).diagnose()
        (f,) = report["findings"]
        assert f["rule"] == "restore_park_stall"
        assert f["evidence"]["lane"] == "restore"
        assert f["evidence"]["parked"] == 3
        assert f["evidence"]["restores_queued"] == 10

    def test_fires_on_audited_park_share(self):
        attr = _attr_with_shapes({
            "p512": (4, 1.0, {"restore_park": 0.6, "decode": 0.2}),
        })
        eng = FakeEngine(parked=0)
        report = MeshDoctor(engine=eng, attributor=attr).diagnose()
        rules = [f["rule"] for f in report["findings"]]
        assert "restore_park_stall" in rules

    def test_silent_when_parked_without_backlog(self):
        assert (
            MeshDoctor(engine=FakeEngine(parked=3, queued=0, staged=0))
            .diagnose()["findings"]
            == []
        )


class TestReplicationLagRule:
    def test_fires_naming_lagging_ranks(self):
        mesh = FakeMesh(sharded=False, lags={0: 0.1, 3: 2.5, 5: 1.2})
        report = MeshDoctor(mesh=mesh).diagnose()
        (f,) = report["findings"]
        assert f["rule"] == "replication_lag"
        assert set(f["evidence"]["ranks"]) == {"3", "5"}
        assert f["evidence"]["worst_lag_s"] == 2.5

    def test_silent_below_threshold(self):
        mesh = FakeMesh(sharded=False, lags={0: 0.9, 1: 0.3})
        assert MeshDoctor(mesh=mesh).diagnose()["findings"] == []


class TestBurnRateRule:
    def test_fires_only_when_both_windows_burn(self):
        clk = FakeClock()
        slo = FakeSLO()
        doctor = MeshDoctor(slo=slo, now=clk)
        admitted = shed = 0
        # One hour of sustained 20% shed at 5s cadence: both the 5m and
        # the 1h windows burn past their thresholds.
        for _ in range(720):
            admitted += 8
            shed += 2
            slo.counts = {"bulk": {"admitted": admitted, "shed": shed}}
            slo.tier = 2
            clk.advance(5.0)
            report = doctor.diagnose()
        (f,) = report["findings"]
        assert f["rule"] == "slo_burn_rate"
        assert f["evidence"]["tenant"] == "bulk"
        assert f["evidence"]["burn_fast"] > DoctorConfig().burn_fast_threshold
        assert f["evidence"]["burn_slow"] > DoctorConfig().burn_slow_threshold
        assert f["evidence"]["tier"] == 2

    def test_short_blip_does_not_page(self):
        clk = FakeClock()
        slo = FakeSLO()
        doctor = MeshDoctor(slo=slo, now=clk)
        admitted = shed = 0
        # 50 minutes clean...
        for _ in range(600):
            admitted += 10
            slo.counts = {"bulk": {"admitted": admitted, "shed": shed}}
            clk.advance(5.0)
            doctor.diagnose()
        # ...then a 30-second storm: the fast window burns, the slow
        # window (diluted by the clean hour) does not → no page.
        for _ in range(6):
            shed += 5
            admitted += 5
            slo.counts = {"bulk": {"admitted": admitted, "shed": shed}}
            clk.advance(5.0)
            report = doctor.diagnose()
        assert report["findings"] == []


class TestSpecEfficiencyRule:
    def test_fires_on_low_acceptance_shape(self):
        eng = FakeEngine(spec={"p128": (200, 20), "p512": (200, 150)})
        report = MeshDoctor(engine=eng).diagnose()
        (f,) = report["findings"]
        assert f["rule"] == "spec_efficiency"
        assert f["evidence"]["shape"] == "p128"
        assert f["evidence"]["proposed"] == 200
        assert f["evidence"]["accepted"] == 20

    def test_silent_below_min_proposals(self):
        eng = FakeEngine(spec={"p128": (30, 0)})
        assert MeshDoctor(engine=eng).diagnose()["findings"] == []


class TestRebalancerAsleepRule:
    """Satellite (PR 14): a SUSTAINED skew peak with zero rebalance
    moves in the same window is a named pathology — the telemetry sees
    a storm nothing is acting on. Virtual-clock driven: sustained means
    seconds above threshold across diagnose samples, never one spike."""

    def _doctor(self, mesh, clock):
        return MeshDoctor(
            mesh=mesh,
            cfg=DoctorConfig(rebalance_window_s=60.0,
                             rebalance_sustain_s=10.0),
            now=clock,
        )

    def test_sustained_skew_with_no_plane_fires(self):
        clk = FakeClock()
        mesh = FakeMesh(skew=9.0, hot_shard=7)
        doctor = self._doctor(mesh, clk)
        assert not [
            f for f in doctor.diagnose()["findings"]
            if f["rule"] == "rebalancer_asleep"
        ]  # a single spike is not sustained
        clk.advance(15.0)
        report = doctor.diagnose()
        (f,) = [
            f for f in report["findings"]
            if f["rule"] == "rebalancer_asleep"
        ]
        ev = f["evidence"]
        assert ev["moves_in_window"] == 0
        assert ev["plane_armed"] is False
        assert ev["hot_shard"] == 7
        assert ev["sustained_s"] >= 10.0
        assert ev["skew_peak"] >= 9.0
        for k in RULE_EVIDENCE_FIELDS["rebalancer_asleep"]:
            assert k in ev

    def test_moves_in_window_silence_the_rule(self):
        clk = FakeClock()
        mesh = FakeMesh(skew=9.0)
        mesh.rebalance = FakeRebalancePlane(moves=2)
        doctor = self._doctor(mesh, clk)
        doctor.diagnose()
        clk.advance(15.0)
        assert not [
            f for f in doctor.diagnose()["findings"]
            if f["rule"] == "rebalancer_asleep"
        ]

    def test_armed_but_idle_plane_still_fires(self):
        clk = FakeClock()
        mesh = FakeMesh(skew=9.0)
        mesh.rebalance = FakeRebalancePlane(moves=0)
        doctor = self._doctor(mesh, clk)
        doctor.diagnose()
        clk.advance(15.0)
        (f,) = [
            f for f in doctor.diagnose()["findings"]
            if f["rule"] == "rebalancer_asleep"
        ]
        assert f["evidence"]["plane_armed"] is True

    def test_short_or_low_skew_stays_silent(self):
        clk = FakeClock()
        doctor = self._doctor(FakeMesh(skew=9.0), clk)
        doctor.diagnose()
        clk.advance(5.0)  # above threshold but not sustained
        assert not [
            f for f in doctor.diagnose()["findings"]
            if f["rule"] == "rebalancer_asleep"
        ]
        clk2 = FakeClock()
        doctor2 = self._doctor(FakeMesh(skew=2.0), clk2)
        doctor2.diagnose()
        clk2.advance(30.0)
        assert not [
            f for f in doctor2.diagnose()["findings"]
            if f["rule"] == "rebalancer_asleep"
        ]

    def test_sparse_self_samples_do_not_smear(self):
        """Review hardening: two momentary spikes seen by diagnose
        calls far apart must NOT read as a sustained storm — a
        self-sampled point's persistence is capped (the BurnRateTracker
        staleness discipline), unlike change-compressed history points
        whose gaps genuinely mean 'unchanged'."""
        clk = FakeClock()
        mesh = FakeMesh(skew=9.0)
        doctor = self._doctor(mesh, clk)
        doctor.diagnose()  # spike 1
        clk.advance(600.0)  # ten quiet minutes nobody looked at
        report = doctor.diagnose()  # spike 2
        assert not [
            f for f in report["findings"]
            if f["rule"] == "rebalancer_asleep"
        ]

    def test_skew_cooldown_resets_the_window(self):
        clk = FakeClock()
        mesh = FakeMesh(skew=9.0)
        doctor = self._doctor(mesh, clk)
        doctor.diagnose()
        clk.advance(6.0)
        mesh._report["skew_score"] = 1.0  # storm cooled before sustain
        doctor.diagnose()
        clk.advance(30.0)
        assert not [
            f for f in doctor.diagnose()["findings"]
            if f["rule"] == "rebalancer_asleep"
        ]


class TestDiagnoseContract:
    def test_absent_seams_drop_rules_from_checked(self):
        # The honesty field: a rule whose input seam is absent never
        # looked at anything, so it must not claim to have run — a bare
        # doctor checked NOTHING, and the report says so.
        report = MeshDoctor().diagnose()
        assert report["findings"] == []
        assert report["healthy"] is True
        assert list(report["rules_checked"]) == []
        assert report["inputs"] == {
            "mesh": False, "engine": False, "slo": False,
            "attribution": False, "history": False, "aggregator": False,
        }

    def test_rules_checked_tracks_attached_seams(self):
        report = MeshDoctor(mesh=FakeMesh(sharded=False)).diagnose()
        assert list(report["rules_checked"]) == [
            "hot_shard", "replication_lag", "rebalancer_asleep",
        ]
        report = MeshDoctor(engine=FakeEngine()).diagnose()
        assert list(report["rules_checked"]) == [
            "restore_park_stall", "spec_efficiency", "tier_thrash",
            "decode_stall", "spec_misconfigured",
        ]

    def test_findings_ranked_by_score(self):
        mesh = FakeMesh(skew=100.0, lags={3: 1.5})
        eng = FakeEngine(parked=2, queued=1)
        report = MeshDoctor(mesh=mesh, engine=eng).diagnose()
        scores = [f["score"] for f in report["findings"]]
        assert scores == sorted(scores, reverse=True)
        assert len(report["findings"]) == 3

    def test_evidence_contract_enforced_live(self):
        # A rule that fires with missing pinned evidence gets flagged in
        # the finding itself, not silently shipped.
        doctor = MeshDoctor(mesh=FakeMesh(skew=9.0))
        orig = doctor._rule_hot_shard

        def degraded():
            f = orig()
            del f.evidence["owners"]
            return f

        doctor._rule_hot_shard = degraded
        (f,) = doctor.diagnose()["findings"]
        assert f["evidence"]["_missing_evidence"] == ["owners"]

    def test_crashed_rule_becomes_a_finding(self):
        class Exploding:
            sharded = True

            def shard_heat_report(self):
                raise RuntimeError("boom")

        report = MeshDoctor(mesh=Exploding()).diagnose()
        crashed = [f for f in report["findings"] if "crashed" in f["summary"]]
        assert crashed and crashed[0]["rule"] == "hot_shard"
        # ...and the mesh's other rule still ran.
        assert list(report["rules_checked"]) == [
            "hot_shard", "replication_lag", "rebalancer_asleep",
        ]

    def test_every_rule_has_pinned_evidence_fields(self):
        assert set(RULE_EVIDENCE_FIELDS) == set(RULES)
        for fields in RULE_EVIDENCE_FIELDS.values():
            assert fields  # never an empty contract

    def test_callable_attributor_seam(self):
        calls = []

        def resolve():
            calls.append(1)
            return None

        doctor = MeshDoctor(attributor=resolve)
        assert doctor.attributor is None
        assert calls

    def test_finding_as_dict_shape(self):
        d = Finding("hot_shard", 0.77777, "s", {"k": 1}).as_dict()
        assert d == {
            "rule": "hot_shard", "score": 0.7778, "summary": "s",
            "evidence": {"k": 1},
        }


class TestHistoryBackedBurn:
    """Satellite (PR 13): the burn windows feed from the telemetry
    history ring, so a SPARSE diagnose cadence can no longer blind the
    rule (the PR 12 can't-judge gap) — and the base is the last sample
    at or before the window start, so stale shed never smears into a
    fresh window. All virtual-time."""

    def _history_fed_doctor(self, clk, slo):
        from radixmesh_tpu.obs.timeseries import TelemetryHistory

        hist = TelemetryHistory(
            interval_s=1.0, capacity=4096, slo=slo, now=clk
        )
        doctor = MeshDoctor(slo=slo, history=hist, now=clk)
        return hist, doctor

    def test_sparse_diagnose_still_judges_both_windows(self):
        # Diagnose only every 10 MINUTES — under PR 12 this returned
        # can't-judge for the 5m window every single time. With the 1 s
        # history feed, the first diagnose after an hour of sustained
        # 20% shed pages on both windows.
        clk = FakeClock()
        slo = FakeSLO()
        hist, doctor = self._history_fed_doctor(clk, slo)
        admitted = shed = 0
        report = None
        for i in range(3600):
            admitted += 8
            shed += 2
            slo.counts = {"bulk": {"admitted": admitted, "shed": shed}}
            clk.advance(1.0)
            hist.sample()  # the sampler thread's tick, virtualized
            if i % 600 == 599:  # one GET /cluster/doctor per 10 min
                report = doctor.diagnose()
        (f,) = report["findings"]
        assert f["rule"] == "slo_burn_rate"
        assert f["evidence"]["burn_fast"] > DoctorConfig().burn_fast_threshold
        assert f["evidence"]["burn_slow"] > DoctorConfig().burn_slow_threshold

    def test_stale_storm_does_not_smear_into_fast_window(self):
        # A storm 50 minutes ago, clean since: the 5m window must read
        # clean at the next (sparse) diagnose — the old first-in-window
        # scan answered can't-judge here, and the pre-PR-12 code smeared
        # the storm in and paged.
        clk = FakeClock()
        slo = FakeSLO()
        hist, doctor = self._history_fed_doctor(clk, slo)
        admitted, shed = 0, 0
        for _ in range(120):  # 2 min of storm
            admitted += 5
            shed += 5
            slo.counts = {"bulk": {"admitted": admitted, "shed": shed}}
            clk.advance(1.0)
            hist.sample()
        for _ in range(3000):  # 50 clean minutes
            admitted += 10
            slo.counts = {"bulk": {"admitted": admitted, "shed": shed}}
            clk.advance(1.0)
            hist.sample()
        report = doctor.diagnose()  # first GET in 50 minutes
        assert report["findings"] == []
        fast, offered = doctor.burn_tracker.burn("bulk", 300.0)
        assert offered > 0  # judged, not can't-judge
        assert fast == pytest.approx(0.0)

    def test_feed_gap_still_refuses_to_smear(self):
        # If the SAMPLER itself dies (no feed at all), the bounded
        # staleness guard keeps the old storm out of the fast window
        # rather than smearing it in.
        clk = FakeClock()
        bt = BurnRateTracker(budget=0.01, now=clk)
        bt.sample({"t0": {"admitted": 0, "shed": 0}})
        clk.advance(10)
        bt.sample({"t0": {"admitted": 0, "shed": 100}})  # old storm
        clk.advance(3000)  # 50 min of silence: sampler dead
        bt.sample({"t0": {"admitted": 100, "shed": 100}})
        burn, offered = bt.burn("t0", 300.0)
        assert (burn, offered) == (0.0, 0)  # can't judge > smear

    def test_diagnose_does_not_double_sample_with_history(self):
        clk = FakeClock()
        slo = FakeSLO()
        hist, doctor = self._history_fed_doctor(clk, slo)
        slo.counts = {"t": {"admitted": 10, "shed": 0}}
        clk.advance(1.0)
        hist.sample()
        dq_before = len(doctor.burn_tracker._samples.get("t", ()))
        doctor.diagnose()
        assert len(doctor.burn_tracker._samples.get("t", ())) == dq_before

    def test_inputs_report_history_attachment(self):
        clk = FakeClock()
        slo = FakeSLO()
        hist, doctor = self._history_fed_doctor(clk, slo)
        assert doctor.diagnose()["inputs"]["history"] is True

    def test_slo_less_history_falls_back_to_self_sampling(self):
        # A doctor handed an slo seam plus a history built WITHOUT one
        # must not bind to the (never-firing) sampler feed and go blind:
        # the burn rule keeps self-sampling at diagnose time.
        from radixmesh_tpu.obs.timeseries import TelemetryHistory

        clk = FakeClock()
        slo = FakeSLO()
        hist = TelemetryHistory(interval_s=1.0, capacity=4096, now=clk)
        doctor = MeshDoctor(slo=slo, history=hist, now=clk)
        slo.counts = {"t": {"admitted": 10, "shed": 0}}
        clk.advance(1.0)
        hist.sample()  # sampler tick: no slo seam, forwards nothing
        assert len(doctor.burn_tracker._samples.get("t", ())) == 0
        doctor.diagnose()
        assert len(doctor.burn_tracker._samples.get("t", ())) == 1
        # And a sustained storm judged through dense diagnoses pages.
        admitted = shed = 0
        report = None
        for _ in range(720):
            admitted += 8
            shed += 2
            slo.counts = {"t": {"admitted": admitted, "shed": shed}}
            clk.advance(5.0)
            report = doctor.diagnose()
        (f,) = report["findings"]
        assert f["rule"] == "slo_burn_rate"


class _TokenEngine:
    """Engine stand-in for the token-plane rules: a real TokenTimeline
    and SpecLedger hung off the attributes the doctor duck-types."""

    def __init__(self, spec_decode_tokens=4):
        from radixmesh_tpu.obs.token_timeline import (
            SpecLedger,
            TokenTimeline,
        )

        self.timeline = TokenTimeline(
            capacity=256, stall_threshold_s=0.05, node="fx"
        )
        self.spec_ledger = SpecLedger(node="fx")
        self.spec_decode_tokens = spec_decode_tokens

    def spec_report(self):
        return {}  # keeps the raw-counter spec_efficiency rule silent


class TestDecodeStallRule:
    """Tentpole (PR 18): the token-timeline stall histogram pages with
    the DOMINANT cause named — the per-token refinement of
    restore_park_stall."""

    def test_fires_with_dominant_cause(self):
        eng = _TokenEngine()
        for i in range(12):
            eng.timeline.note_token(
                i, "default", 0.2, cause="restore_park", now=float(i)
            )
        eng.timeline.note_token(99, "default", 0.2, cause="spec_verify_miss",
                                now=99.0)
        (f,) = MeshDoctor(engine=eng).diagnose()["findings"]
        assert f["rule"] == "decode_stall"
        assert f["evidence"]["cause"] == "restore_park"
        assert f["evidence"]["stalls"] == 13
        assert f["evidence"]["stall_seconds"] == pytest.approx(2.4)
        assert f["evidence"]["threshold_s"] == 0.05
        assert f["evidence"]["p99_itl_s"] >= 0.2

    def test_silent_below_min_events(self):
        eng = _TokenEngine()
        for i in range(DoctorConfig().decode_stall_min_events - 1):
            eng.timeline.note_token(
                i, "default", 0.2, cause="scheduler_wait", now=float(i)
            )
        report = MeshDoctor(engine=eng).diagnose()
        assert report["findings"] == []
        # Vacuous-pass honesty: the rule RAN and found nothing.
        assert "decode_stall" in report["rules_checked"]

    def test_silent_on_fast_tokens(self):
        eng = _TokenEngine()
        for i in range(100):
            eng.timeline.note_token(i, "default", 0.002, now=float(i))
        assert MeshDoctor(engine=eng).diagnose()["findings"] == []


class TestSpecMisconfiguredRule:
    """Tentpole (PR 18): γ and EWMA acceptance diverging on a ledger
    class pages — but never when the SLO ladder zeroed γ on purpose."""

    def _miss_waves(self, eng, n=30):
        for _ in range(n):
            eng.spec_ledger.note_wave(
                "default", "p32", "ngram", proposed=4, accepted=0, gamma=4
            )

    def test_fires_on_low_ewma_wide_gamma(self):
        eng = _TokenEngine()
        self._miss_waves(eng)
        (f,) = MeshDoctor(engine=eng).diagnose()["findings"]
        assert f["rule"] == "spec_misconfigured"
        ev = f["evidence"]
        assert (ev["tenant"], ev["shape"], ev["source"]) == (
            "default", "p32", "ngram",
        )
        assert ev["gamma"] == 4
        assert ev["accept_ewma"] == pytest.approx(0.0)
        assert ev["proposed"] == 120

    def test_silent_when_tier_zeroed_gamma(self):
        # The SLO ladder shed speculation deliberately: not a mistuning.
        eng = _TokenEngine()
        self._miss_waves(eng)
        eng.spec_ledger.note_tier(1)
        assert MeshDoctor(engine=eng).diagnose()["findings"] == []

    def test_silent_when_spec_off(self):
        eng = _TokenEngine(spec_decode_tokens=0)
        self._miss_waves(eng)
        assert MeshDoctor(engine=eng).diagnose()["findings"] == []

    def test_silent_below_min_proposed(self):
        eng = _TokenEngine()
        self._miss_waves(eng, n=5)  # 20 proposed < the 50 floor
        report = MeshDoctor(engine=eng).diagnose()
        assert report["findings"] == []
        assert "spec_misconfigured" in report["rules_checked"]

    def test_silent_on_healthy_acceptance(self):
        eng = _TokenEngine()
        for _ in range(30):
            eng.spec_ledger.note_wave(
                "default", "p32", "tree", proposed=4, accepted=4, gamma=4
            )
        assert MeshDoctor(engine=eng).diagnose()["findings"] == []


class _FakeGoodputHistory:
    """History-ring stand-in serving one synthetic
    ``goodput:tokens_per_second`` series; points are (seq, t, value)."""

    def __init__(self, points):
        self._points = list(points)

    def query(self, family=None, limit=0):
        assert family == "goodput:tokens_per_second"
        return {
            "series": {
                "goodput:tokens_per_second": {"points": list(self._points)}
            }
        }


class TestGoodputRegressionRule:
    """Tentpole (PR 18): recent-window mean tokens/s collapsing below
    the baseline window pages with the drop fraction pinned."""

    def test_fires_on_collapse(self):
        # Baseline 100 tok/s inside [now-300, now-60), then 10 tok/s
        # for the last minute: a 90% drop.
        pts = [(i, 100.0 + i * 5.0, 100.0) for i in range(40)]
        pts += [(40 + i, 310.0 + i * 10.0, 10.0) for i in range(6)]
        hist = _FakeGoodputHistory(pts)
        report = MeshDoctor(history=hist).diagnose()
        found = [
            f for f in report["findings"] if f["rule"] == "goodput_regression"
        ]
        (f,) = found
        assert f["evidence"]["recent_tps"] == pytest.approx(10.0)
        assert f["evidence"]["baseline_tps"] == pytest.approx(100.0)
        assert f["evidence"]["drop_frac"] == pytest.approx(0.9)
        assert f["evidence"]["window_s"] == 60.0

    def test_silent_on_steady_throughput(self):
        pts = [(i, 100.0 + i * 5.0, 100.0) for i in range(60)]
        hist = _FakeGoodputHistory(pts)
        report = MeshDoctor(history=hist).diagnose()
        assert not [
            f for f in report["findings"] if f["rule"] == "goodput_regression"
        ]
        # Vacuous-pass honesty: the history seam armed the rule.
        assert "goodput_regression" in report["rules_checked"]

    def test_silent_on_idle_baseline(self):
        # Baseline under goodput_min_tps: nothing to regress FROM —
        # an idle mesh starting work must not page.
        pts = [(i, 100.0 + i * 5.0, 0.1) for i in range(40)]
        pts += [(40 + i, 310.0 + i * 10.0, 0.0) for i in range(6)]
        report = MeshDoctor(history=_FakeGoodputHistory(pts)).diagnose()
        assert not [
            f for f in report["findings"] if f["rule"] == "goodput_regression"
        ]

    def test_silent_on_empty_series(self):
        class Empty:
            def query(self, family=None, limit=0):
                return {"series": {}}

        assert not [
            f
            for f in MeshDoctor(history=Empty()).diagnose()["findings"]
            if f["rule"] == "goodput_regression"
        ]
