"""meshcheck: the AST-based static-analysis plane's quick gate.

``test_tree_is_clean`` IS the CI gate: every checker over every product
file, zero unsuppressed findings. The rest of the file proves the gate
means something — each positive-control fixture (a deliberately broken
mini package tree under ``tests/fixtures/analysis/``) must trip its
checker with the right invariant-id and file:line, and the
justification-comment grammar must suppress exactly what it names,
flag what it fails to justify, and rot-proof itself (stale
suppressions are findings).
"""

from __future__ import annotations

import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from radixmesh_tpu.analysis import all_checkers
from radixmesh_tpu.analysis.controls import (
    default_fixtures_root,
    run_positive_controls,
)
from radixmesh_tpu.analysis.core import SourceIndex, run_checkers

pytestmark = pytest.mark.quick

_REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# THE gate
# ---------------------------------------------------------------------------

def test_tree_is_clean():
    """Every checker, every product file, zero unsuppressed findings.
    (Suppression requires an in-source justification comment; a stale
    or malformed one is itself a finding, so this single assertion also
    pins the excuse ledger.)"""
    from radixmesh_tpu.analysis import check_tree

    result = check_tree()
    assert result.clean, "\n" + result.pretty()


# ---------------------------------------------------------------------------
# positive controls: the checkers still SEE the seeded bug classes
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def controls():
    out = run_positive_controls()
    assert out, "no positive-control fixtures found under tests/fixtures/analysis"
    return out


def _tripped(controls, fixture, invariant):
    hits = [
        c for c in controls
        if c.fixture == fixture and c.invariant == invariant
    ]
    assert hits, f"no seeded marker for {invariant} in fixture {fixture!r}"
    missed = [c for c in hits if not c.tripped]
    assert not missed, (
        f"checker went blind: {[(c.file, c.line, c.invariant) for c in missed]}"
    )
    return hits


class TestPositiveControls:
    def test_all_controls_tripped(self, controls):
        missed = [c for c in controls if not c.tripped]
        assert not missed, [
            f"{c.fixture}: {c.invariant} at {c.file}:{c.line}" for c in missed
        ]

    def test_seeded_deadlock_cycle(self, controls):
        """The helper-nested lock cycle — B→A lives behind a call, which
        no grep can see — trips with file:line on the cycle edge."""
        hits = _tripped(controls, "lock_cycle", "lock-order-cycle")
        assert hits[0].file == "engine/engine.py"
        assert hits[0].line > 0

    def test_seeded_aliased_writers(self, controls):
        """Aliased lifecycle write, aliased heat counter, private
        OwnershipMap construction + owner-set poke."""
        _tripped(controls, "single_writer_alias", "single-writer-lifecycle")
        _tripped(controls, "single_writer_alias", "single-writer-heat")
        hits = _tripped(
            controls, "single_writer_alias", "single-writer-ownership"
        )
        assert {c.line for c in hits} == {8, 13}  # construction AND poke

    def test_seeded_hotpath_sleep(self, controls):
        """time.sleep two frames below Engine.step — reachable through
        the call graph, invisible to any module-scoped grep."""
        hits = _tripped(controls, "hotpath_sleep", "hotpath-blocking")
        assert hits[0].file == "engine/engine.py"

    def test_seeded_unregistered_oplog_kind(self, controls):
        hits = _tripped(controls, "wire_unregistered", "wire-unregistered")
        assert hits[0].file == "cache/oplog.py"

    def test_seeded_unprefixed_metric(self, controls):
        _tripped(controls, "metrics_vocab", "metrics-prefix")
        _tripped(controls, "metrics_vocab", "metrics-unit")
        _tripped(controls, "metrics_vocab", "metrics-literal")

    def test_seeded_send_seam_breaches(self, controls):
        hits = _tripped(controls, "send_seam", "send-seam")
        # Both the raw .send( AND the out-of-seam try_send trip; the
        # _sender_loop try_send in the same fixture does NOT.
        assert len(hits) == 2

    def test_seeded_unjustified_suppression(self, controls):
        """An ok[...] directive with no justification is a finding and
        suppresses nothing (the sleep beneath it still trips)."""
        _tripped(controls, "suppression_grammar", "suppression-grammar")
        _tripped(controls, "suppression_grammar", "sleep-audit")


# ---------------------------------------------------------------------------
# suppression grammar, live
# ---------------------------------------------------------------------------

def _run_on(tmp_path: Path, rel: str, source: str):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    index = SourceIndex(tmp_path)
    return run_checkers(index, all_checkers())


class TestSuppressionGrammar:
    def test_justified_suppression_suppresses(self, tmp_path):
        res = _run_on(tmp_path, "utils/poll.py", """\
            import time

            def backoff():
                # meshcheck: ok[sleep-audit] test: bounded retry pacing
                time.sleep(0.1)
            """)
        assert res.clean, res.pretty()
        assert len(res.suppressed) == 1
        finding, sup = res.suppressed[0]
        assert finding.invariant == "sleep-audit"
        assert sup.justification == "test: bounded retry pacing"

    def test_suppression_only_covers_named_invariant(self, tmp_path):
        res = _run_on(tmp_path, "utils/poll.py", """\
            import time

            def backoff(q):
                # meshcheck: ok[timeout-audit] wrong invariant named
                time.sleep(0.1)
            """)
        # The sleep is NOT excused (directive names a different id) and
        # the directive is stale (it excused nothing).
        invs = {f.invariant for f in res.findings}
        assert invs == {"sleep-audit", "stale-suppression"}, res.pretty()

    def test_file_scope_suppression(self, tmp_path):
        res = _run_on(tmp_path, "utils/gen.py", """\
            # meshcheck: file-ok[sleep-audit] test: generator paces by design
            import time

            def a():
                time.sleep(0.1)

            def b():
                time.sleep(0.2)
            """)
        assert res.clean, res.pretty()
        assert len(res.suppressed) == 2

    def test_malformed_directive_is_a_finding(self, tmp_path):
        res = _run_on(tmp_path, "utils/bad.py", """\
            def f():
                # meshcheck: ok[sleep-audit]
                return 1
            """)
        invs = [f.invariant for f in res.findings]
        assert invs == ["suppression-grammar"], res.pretty()

    def test_stale_suppression_is_a_finding(self, tmp_path):
        """The rot-proofing the old grep allowlists did by hand
        (``test_allowlist_entries_still_match``), framework-enforced."""
        res = _run_on(tmp_path, "utils/clean.py", """\
            def f():
                # meshcheck: ok[sleep-audit] excuse with nothing beneath it
                return 1
            """)
        invs = [f.invariant for f in res.findings]
        assert invs == ["stale-suppression"], res.pretty()

    def test_multiline_justification_block_covers_next_statement(self, tmp_path):
        res = _run_on(tmp_path, "utils/poll.py", """\
            import time

            def backoff():
                # meshcheck: ok[sleep-audit] the justification continues
                # onto a second line and still anchors to the statement
                # after the comment block.
                time.sleep(0.1)
            """)
        assert res.clean, res.pretty()


# ---------------------------------------------------------------------------
# grep-invisible cases, live (not via fixtures): the two bug shapes the
# ISSUE names as motivating the AST rewrite
# ---------------------------------------------------------------------------

class TestGrepInvisible:
    def test_helper_nested_lock_cycle_detected(self, tmp_path):
        res = _run_on(tmp_path, "cache/plane.py", """\
            import threading

            class Plane:
                def __init__(self):
                    self._state = threading.Lock()
                    self._io = threading.Lock()

                def flush(self):
                    with self._state:
                        self._emit()

                def _emit(self):
                    with self._io:
                        pass

                def reload(self):
                    with self._io:
                        with self._state:
                            pass
            """)
        cycles = [f for f in res.findings if f.invariant == "lock-order-cycle"]
        assert cycles, res.pretty()
        assert "_state" in cycles[0].message and "_io" in cycles[0].message

    def test_nonreentrant_self_deadlock_through_helper(self, tmp_path):
        res = _run_on(tmp_path, "cache/plane.py", """\
            import threading

            class Plane:
                def __init__(self):
                    self._lock = threading.Lock()

                def get(self, k):
                    with self._lock:
                        return self._slow(k)

                def _slow(self, k):
                    with self._lock:
                        return k
            """)
        invs = {f.invariant for f in res.findings}
        assert "lock-order-reentry" in invs, res.pretty()

    def test_rlock_reentry_is_legal(self, tmp_path):
        res = _run_on(tmp_path, "cache/plane.py", """\
            import threading

            class Plane:
                def __init__(self):
                    self._lock = threading.RLock()

                def get(self, k):
                    with self._lock:
                        return self._slow(k)

                def _slow(self, k):
                    with self._lock:
                        return k
            """)
        assert res.clean, res.pretty()

    def test_aliased_lifecycle_write_detected(self, tmp_path):
        res = _run_on(tmp_path, "server/rogue.py", """\
            from radixmesh_tpu.policy.lifecycle import LifecycleState

            def force_active(plane):
                target = LifecycleState.ACTIVE
                plane.state = target
            """)
        hits = [
            f for f in res.findings
            if f.invariant == "single-writer-lifecycle"
        ]
        assert len(hits) == 2, res.pretty()  # the binding AND the store

    def test_lifecycle_comparisons_stay_legal(self, tmp_path):
        res = _run_on(tmp_path, "server/reader.py", """\
            from radixmesh_tpu.policy.lifecycle import LifecycleState

            def is_active(plane):
                draining = plane.state is LifecycleState.DRAINING
                return not draining and (
                    plane.code == LifecycleState.ACTIVE.value
                )
            """)
        assert res.clean, res.pretty()

    def test_setattr_write_detected(self, tmp_path):
        res = _run_on(tmp_path, "server/rogue.py", """\
            from radixmesh_tpu.policy.lifecycle import LifecycleState

            def sneak(plane):
                setattr(plane, "state", LifecycleState.ACTIVE)
            """)
        invs = {f.invariant for f in res.findings}
        assert "single-writer-lifecycle" in invs, res.pretty()

    def test_bare_imported_sleep_detected(self, tmp_path):
        """``from time import sleep; sleep(x)`` must not evade the
        audit the dotted-name match would miss."""
        res = _run_on(tmp_path, "engine/engine.py", """\
            from time import sleep

            class Engine:
                def step(self):
                    sleep(0.25)
            """)
        invs = {f.invariant for f in res.findings}
        assert "hotpath-blocking" in invs, res.pretty()

    def test_block_true_get_is_unbounded(self, tmp_path):
        """``q.get(True)`` passes the block FLAG, not a timeout — it
        parks forever and must trip like a bare get()."""
        res = _run_on(tmp_path, "engine/engine.py", """\
            class Engine:
                def __init__(self, q):
                    self._q = q

                def step(self):
                    return self._q.get(True)

                def drain(self):
                    return self._q.get(block=True)
            """)
        hot = [f for f in res.findings if f.invariant == "hotpath-blocking"]
        audit = [f for f in res.findings if f.invariant == "timeout-audit"]
        assert hot and audit, res.pretty()

    def test_aliased_store_after_nested_binding(self, tmp_path):
        """The alias pass is order-independent: a store that lexically
        follows a binding nested in a deeper block still trips."""
        res = _run_on(tmp_path, "server/rogue.py", """\
            from radixmesh_tpu.policy.lifecycle import LifecycleState

            def force(plane, cond):
                if cond:
                    st = LifecycleState.ACTIVE
                plane.state = st
            """)
        hits = [
            f for f in res.findings
            if f.invariant == "single-writer-lifecycle"
        ]
        assert len(hits) == 2, res.pretty()

    def test_serving_entry_points_still_resolve(self):
        """The hot-path checker's roots are pinned: a rename that
        silently dropped an entry point would hollow out the whole
        call-graph plane while everything stayed green (the same
        rot class stale-suppression guards against, for the checker's
        own config)."""
        import ast

        from radixmesh_tpu.analysis import tree_index
        from radixmesh_tpu.analysis.hot_path import DEFAULT_ENTRY_POINTS

        index = tree_index()
        for rel, qual in DEFAULT_ENTRY_POINTS:
            assert rel in index, f"entry-point module {rel} vanished"
            tree = index.module(rel).tree
            cls, _, meth = qual.partition(".")
            found = any(
                isinstance(n, ast.ClassDef) and n.name == cls
                and any(
                    isinstance(m, ast.FunctionDef) and m.name == meth
                    for m in n.body
                )
                for n in tree.body
            )
            assert found, f"entry point {rel}:{qual} no longer resolves"

    def test_blocking_call_two_frames_down(self, tmp_path):
        """Entry point -> helper -> helper -> unbounded queue get."""
        res = _run_on(tmp_path, "engine/engine.py", """\
            class Engine:
                def __init__(self, q):
                    self._q = q

                def step(self):
                    self._admit()

                def _admit(self):
                    self._take_one()

                def _take_one(self):
                    return self._q.get()
            """)
        hot = [f for f in res.findings if f.invariant == "hotpath-blocking"]
        assert hot, res.pretty()
        assert "Engine.step" in hot[0].message  # the chain is named

    def test_bounded_get_stays_legal(self, tmp_path):
        res = _run_on(tmp_path, "engine/engine.py", """\
            class Engine:
                def __init__(self, q):
                    self._q = q

                def step(self):
                    return self._q.get(timeout=0.05)
            """)
        assert res.clean, res.pretty()


# ---------------------------------------------------------------------------
# the CLI is the same plane
# ---------------------------------------------------------------------------

def test_meshcheck_cli_exit_zero_on_clean_tree():
    proc = subprocess.run(
        [sys.executable, str(_REPO / "scripts" / "meshcheck.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout
    assert "controls tripped" in proc.stdout
