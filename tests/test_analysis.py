"""meshcheck: the AST-based static-analysis plane's quick gate.

``test_tree_is_clean`` IS the CI gate: every checker over every product
file, zero unsuppressed findings. The rest of the file proves the gate
means something — each positive-control fixture (a deliberately broken
mini package tree under ``tests/fixtures/analysis/``) must trip its
checker with the right invariant-id and file:line, and the
justification-comment grammar must suppress exactly what it names,
flag what it fails to justify, and rot-proof itself (stale
suppressions are findings).
"""

from __future__ import annotations

import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from radixmesh_tpu.analysis import all_checkers
from radixmesh_tpu.analysis.controls import (
    default_fixtures_root,
    run_positive_controls,
)
from radixmesh_tpu.analysis.core import SourceIndex, run_checkers

pytestmark = pytest.mark.quick

_REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# THE gate
# ---------------------------------------------------------------------------

def test_tree_is_clean():
    """Every checker, every product file, zero unsuppressed findings.
    (Suppression requires an in-source justification comment; a stale
    or malformed one is itself a finding, so this single assertion also
    pins the excuse ledger.)"""
    from radixmesh_tpu.analysis import check_tree

    result = check_tree()
    assert result.clean, "\n" + result.pretty()


# ---------------------------------------------------------------------------
# positive controls: the checkers still SEE the seeded bug classes
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def controls():
    out = run_positive_controls()
    assert out, "no positive-control fixtures found under tests/fixtures/analysis"
    return out


def _tripped(controls, fixture, invariant):
    hits = [
        c for c in controls
        if c.fixture == fixture and c.invariant == invariant
    ]
    assert hits, f"no seeded marker for {invariant} in fixture {fixture!r}"
    missed = [c for c in hits if not c.tripped]
    assert not missed, (
        f"checker went blind: {[(c.file, c.line, c.invariant) for c in missed]}"
    )
    return hits


class TestPositiveControls:
    def test_all_controls_tripped(self, controls):
        missed = [c for c in controls if not c.tripped]
        assert not missed, [
            f"{c.fixture}: {c.invariant} at {c.file}:{c.line}" for c in missed
        ]

    def test_seeded_deadlock_cycle(self, controls):
        """The helper-nested lock cycle — B→A lives behind a call, which
        no grep can see — trips with file:line on the cycle edge."""
        hits = _tripped(controls, "lock_cycle", "lock-order-cycle")
        assert hits[0].file == "engine/engine.py"
        assert hits[0].line > 0

    def test_seeded_aliased_writers(self, controls):
        """Aliased lifecycle write, aliased heat counter, private
        OwnershipMap construction + owner-set poke."""
        _tripped(controls, "single_writer_alias", "single-writer-lifecycle")
        _tripped(controls, "single_writer_alias", "single-writer-heat")
        hits = _tripped(
            controls, "single_writer_alias", "single-writer-ownership"
        )
        assert {c.line for c in hits} == {8, 13}  # construction AND poke

    def test_seeded_override_second_writer(self, controls):
        """PR 14: the rebalance plane's single-writer contract is
        enforced, not aspirational — a private ShardOverrides
        construction AND a .moves poke both trip."""
        hits = _tripped(
            controls, "single_writer_alias", "single-writer-overrides"
        )
        assert len(hits) == 2

    def test_seeded_hotpath_sleep(self, controls):
        """time.sleep two frames below Engine.step — reachable through
        the call graph, invisible to any module-scoped grep."""
        hits = _tripped(controls, "hotpath_sleep", "hotpath-blocking")
        assert hits[0].file == "engine/engine.py"

    def test_seeded_hotpath_file_io(self, controls):
        """PR 15's durable-tier boundary: an extent read (builtin open)
        two frames below Engine.step AND an os.fsync below
        Engine.enqueue both trip — the lint pin that keeps disk I/O on
        the KV-plane worker, never the serving loop."""
        hits = _tripped(controls, "hotpath_file_io", "hotpath-file-io")
        assert len(hits) == 2
        assert all(h.file == "engine/engine.py" for h in hits)

    def test_seeded_unregistered_oplog_kind(self, controls):
        hits = _tripped(controls, "wire_unregistered", "wire-unregistered")
        assert hits[0].file == "cache/oplog.py"

    def test_seeded_unprefixed_metric(self, controls):
        _tripped(controls, "metrics_vocab", "metrics-prefix")
        _tripped(controls, "metrics_vocab", "metrics-unit")
        _tripped(controls, "metrics_vocab", "metrics-literal")

    def test_seeded_send_seam_breaches(self, controls):
        hits = _tripped(controls, "send_seam", "send-seam")
        # Both the raw .send( AND the out-of-seam try_send trip; the
        # _sender_loop try_send in the same fixture does NOT.
        assert len(hits) == 2

    def test_seeded_unjustified_suppression(self, controls):
        """An ok[...] directive with no justification is a finding and
        suppresses nothing (the sleep beneath it still trips)."""
        _tripped(controls, "suppression_grammar", "suppression-grammar")
        _tripped(controls, "suppression_grammar", "sleep-audit")

    def test_seeded_offlock_write_two_helpers_down(self, controls):
        """The guarded-by race: an off-lock write two helper frames
        below its thread root, and a write reachable from two roots
        with no common lock — both grep-invisible."""
        hits = _tripped(controls, "guarded_race", "guarded-by-race")
        assert {c.file for c in hits} == {"cache/plane.py"}
        assert len(hits) == 2  # the helper-nested pop AND the split-lock write

    def test_seeded_thread_escapes(self, controls):
        """A lambda thread target escapes the map (blinding every
        downstream concurrency verdict); a daemonless spawn wedges
        shutdown."""
        _tripped(controls, "thread_escape", "thread-target-unresolved")
        _tripped(controls, "thread_escape", "thread-daemonless")

    def test_seeded_protocol_drift(self, controls):
        """An undeclared LEFT→ACTIVE revival (source known from the
        enclosing compare), a state with no exit edge, and a dispatch
        that silently drops two declared RequestStates."""
        hits = _tripped(
            controls, "protocol_drift", "protocol-undeclared-transition"
        )
        assert hits[0].file == "policy/lifecycle.py"
        _tripped(controls, "protocol_drift", "protocol-no-exit")
        hits = _tripped(controls, "protocol_drift", "protocol-unhandled-state")
        assert hits[0].file == "engine/engine.py"

    def test_seeded_dead_metric(self, controls):
        _tripped(controls, "metrics_vocab", "metrics-dead")


# ---------------------------------------------------------------------------
# suppression grammar, live
# ---------------------------------------------------------------------------

def _run_on(tmp_path: Path, rel: str, source: str):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    index = SourceIndex(tmp_path)
    return run_checkers(index, all_checkers())


class TestSuppressionGrammar:
    def test_justified_suppression_suppresses(self, tmp_path):
        res = _run_on(tmp_path, "utils/poll.py", """\
            import time

            def backoff():
                # meshcheck: ok[sleep-audit] test: bounded retry pacing
                time.sleep(0.1)
            """)
        assert res.clean, res.pretty()
        assert len(res.suppressed) == 1
        finding, sup = res.suppressed[0]
        assert finding.invariant == "sleep-audit"
        assert sup.justification == "test: bounded retry pacing"

    def test_suppression_only_covers_named_invariant(self, tmp_path):
        res = _run_on(tmp_path, "utils/poll.py", """\
            import time

            def backoff(q):
                # meshcheck: ok[timeout-audit] wrong invariant named
                time.sleep(0.1)
            """)
        # The sleep is NOT excused (directive names a different id) and
        # the directive is stale (it excused nothing).
        invs = {f.invariant for f in res.findings}
        assert invs == {"sleep-audit", "stale-suppression"}, res.pretty()

    def test_file_scope_suppression(self, tmp_path):
        res = _run_on(tmp_path, "utils/gen.py", """\
            # meshcheck: file-ok[sleep-audit] test: generator paces by design
            import time

            def a():
                time.sleep(0.1)

            def b():
                time.sleep(0.2)
            """)
        assert res.clean, res.pretty()
        assert len(res.suppressed) == 2

    def test_malformed_directive_is_a_finding(self, tmp_path):
        res = _run_on(tmp_path, "utils/bad.py", """\
            def f():
                # meshcheck: ok[sleep-audit]
                return 1
            """)
        invs = [f.invariant for f in res.findings]
        assert invs == ["suppression-grammar"], res.pretty()

    def test_stale_suppression_is_a_finding(self, tmp_path):
        """The rot-proofing the old grep allowlists did by hand
        (``test_allowlist_entries_still_match``), framework-enforced."""
        res = _run_on(tmp_path, "utils/clean.py", """\
            def f():
                # meshcheck: ok[sleep-audit] excuse with nothing beneath it
                return 1
            """)
        invs = [f.invariant for f in res.findings]
        assert invs == ["stale-suppression"], res.pretty()

    def test_multiline_justification_block_covers_next_statement(self, tmp_path):
        res = _run_on(tmp_path, "utils/poll.py", """\
            import time

            def backoff():
                # meshcheck: ok[sleep-audit] the justification continues
                # onto a second line and still anchors to the statement
                # after the comment block.
                time.sleep(0.1)
            """)
        assert res.clean, res.pretty()


# ---------------------------------------------------------------------------
# grep-invisible cases, live (not via fixtures): the two bug shapes the
# ISSUE names as motivating the AST rewrite
# ---------------------------------------------------------------------------

class TestGrepInvisible:
    def test_helper_nested_lock_cycle_detected(self, tmp_path):
        res = _run_on(tmp_path, "cache/plane.py", """\
            import threading

            class Plane:
                def __init__(self):
                    self._state = threading.Lock()
                    self._io = threading.Lock()

                def flush(self):
                    with self._state:
                        self._emit()

                def _emit(self):
                    with self._io:
                        pass

                def reload(self):
                    with self._io:
                        with self._state:
                            pass
            """)
        cycles = [f for f in res.findings if f.invariant == "lock-order-cycle"]
        assert cycles, res.pretty()
        assert "_state" in cycles[0].message and "_io" in cycles[0].message

    def test_nonreentrant_self_deadlock_through_helper(self, tmp_path):
        res = _run_on(tmp_path, "cache/plane.py", """\
            import threading

            class Plane:
                def __init__(self):
                    self._lock = threading.Lock()

                def get(self, k):
                    with self._lock:
                        return self._slow(k)

                def _slow(self, k):
                    with self._lock:
                        return k
            """)
        invs = {f.invariant for f in res.findings}
        assert "lock-order-reentry" in invs, res.pretty()

    def test_rlock_reentry_is_legal(self, tmp_path):
        res = _run_on(tmp_path, "cache/plane.py", """\
            import threading

            class Plane:
                def __init__(self):
                    self._lock = threading.RLock()

                def get(self, k):
                    with self._lock:
                        return self._slow(k)

                def _slow(self, k):
                    with self._lock:
                        return k
            """)
        assert res.clean, res.pretty()

    def test_aliased_lifecycle_write_detected(self, tmp_path):
        res = _run_on(tmp_path, "server/rogue.py", """\
            from radixmesh_tpu.policy.lifecycle import LifecycleState

            def force_active(plane):
                target = LifecycleState.ACTIVE
                plane.state = target
            """)
        hits = [
            f for f in res.findings
            if f.invariant == "single-writer-lifecycle"
        ]
        assert len(hits) == 2, res.pretty()  # the binding AND the store

    def test_lifecycle_comparisons_stay_legal(self, tmp_path):
        res = _run_on(tmp_path, "server/reader.py", """\
            from radixmesh_tpu.policy.lifecycle import LifecycleState

            def is_active(plane):
                draining = plane.state is LifecycleState.DRAINING
                return not draining and (
                    plane.code == LifecycleState.ACTIVE.value
                )
            """)
        assert res.clean, res.pretty()

    def test_setattr_write_detected(self, tmp_path):
        res = _run_on(tmp_path, "server/rogue.py", """\
            from radixmesh_tpu.policy.lifecycle import LifecycleState

            def sneak(plane):
                setattr(plane, "state", LifecycleState.ACTIVE)
            """)
        invs = {f.invariant for f in res.findings}
        assert "single-writer-lifecycle" in invs, res.pretty()

    def test_bare_imported_sleep_detected(self, tmp_path):
        """``from time import sleep; sleep(x)`` must not evade the
        audit the dotted-name match would miss."""
        res = _run_on(tmp_path, "engine/engine.py", """\
            from time import sleep

            class Engine:
                def step(self):
                    sleep(0.25)
            """)
        invs = {f.invariant for f in res.findings}
        assert "hotpath-blocking" in invs, res.pretty()

    def test_block_true_get_is_unbounded(self, tmp_path):
        """``q.get(True)`` passes the block FLAG, not a timeout — it
        parks forever and must trip like a bare get()."""
        res = _run_on(tmp_path, "engine/engine.py", """\
            class Engine:
                def __init__(self, q):
                    self._q = q

                def step(self):
                    return self._q.get(True)

                def drain(self):
                    return self._q.get(block=True)
            """)
        hot = [f for f in res.findings if f.invariant == "hotpath-blocking"]
        audit = [f for f in res.findings if f.invariant == "timeout-audit"]
        assert hot and audit, res.pretty()

    def test_aliased_store_after_nested_binding(self, tmp_path):
        """The alias pass is order-independent: a store that lexically
        follows a binding nested in a deeper block still trips."""
        res = _run_on(tmp_path, "server/rogue.py", """\
            from radixmesh_tpu.policy.lifecycle import LifecycleState

            def force(plane, cond):
                if cond:
                    st = LifecycleState.ACTIVE
                plane.state = st
            """)
        hits = [
            f for f in res.findings
            if f.invariant == "single-writer-lifecycle"
        ]
        assert len(hits) == 2, res.pretty()

    def test_serving_entry_points_still_resolve(self):
        """The hot-path checker's roots are pinned: a rename that
        silently dropped an entry point would hollow out the whole
        call-graph plane while everything stayed green (the same
        rot class stale-suppression guards against, for the checker's
        own config)."""
        import ast

        from radixmesh_tpu.analysis import tree_index
        from radixmesh_tpu.analysis.hot_path import DEFAULT_ENTRY_POINTS

        index = tree_index()
        for rel, qual in DEFAULT_ENTRY_POINTS:
            assert rel in index, f"entry-point module {rel} vanished"
            tree = index.module(rel).tree
            cls, _, meth = qual.partition(".")
            found = any(
                isinstance(n, ast.ClassDef) and n.name == cls
                and any(
                    isinstance(m, ast.FunctionDef) and m.name == meth
                    for m in n.body
                )
                for n in tree.body
            )
            assert found, f"entry point {rel}:{qual} no longer resolves"

    def test_blocking_call_two_frames_down(self, tmp_path):
        """Entry point -> helper -> helper -> unbounded queue get."""
        res = _run_on(tmp_path, "engine/engine.py", """\
            class Engine:
                def __init__(self, q):
                    self._q = q

                def step(self):
                    self._admit()

                def _admit(self):
                    self._take_one()

                def _take_one(self):
                    return self._q.get()
            """)
        hot = [f for f in res.findings if f.invariant == "hotpath-blocking"]
        assert hot, res.pretty()
        assert "Engine.step" in hot[0].message  # the chain is named

    def test_bounded_get_stays_legal(self, tmp_path):
        res = _run_on(tmp_path, "engine/engine.py", """\
            class Engine:
                def __init__(self, q):
                    self._q = q

                def step(self):
                    return self._q.get(timeout=0.05)
            """)
        assert res.clean, res.pretty()


# ---------------------------------------------------------------------------
# concurrency plane, live: the lock-set / thread-root / protocol rules
# on synthetic trees (the shapes the gates must keep legal vs flag)
# ---------------------------------------------------------------------------


class TestGuardedByLive:
    def test_compositional_lock_chain_stays_clean(self, tmp_path):
        """A write three helper frames below the lock acquisition is
        GUARDED — the ambient-set fixpoint follows the chain (the shape
        that would false-positive under naive one-frame analysis:
        oplog_received -> _gc_handle -> _fold -> del)."""
        res = _run_on(tmp_path, "cache/plane.py", """\
            import threading

            class Plane:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._pending = {}
                    self._t = threading.Thread(target=self._loop, daemon=True)
                    self._u = threading.Thread(target=self._other, daemon=True)

                def _loop(self):
                    with self._lock:
                        self._handle()

                def _handle(self):
                    self._fold()

                def _fold(self):
                    self._pending["x"] = 1

                def _other(self):
                    with self._lock:
                        self._pending.pop("x", None)
            """)
        assert res.clean, res.pretty()

    def test_single_root_state_never_fires(self, tmp_path):
        """Engine-thread-only fields are allowed to mix locked and
        unlocked access: one non-multi root cannot race itself."""
        res = _run_on(tmp_path, "cache/plane.py", """\
            import threading

            class Plane:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0
                    self._t = threading.Thread(target=self._loop, daemon=True)

                def _loop(self):
                    with self._lock:
                        self._n = 1
                    with self._lock:
                        self._n = 2
                    self._n = 3
            """)
        assert res.clean, res.pretty()

    def test_deviant_read_against_unanimous_convention(self, tmp_path):
        """Every access but one holds the guard, a guarded write runs on
        another thread → the deviant read is a read-write race."""
        res = _run_on(tmp_path, "cache/plane.py", """\
            import threading

            class Plane:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._map = {}
                    self._t = threading.Thread(target=self._loop, daemon=True)

                def _loop(self):
                    with self._lock:
                        self._map["k"] = 1
                    with self._lock:
                        self._map["j"] = 2

                def snapshot(self):
                    return dict(self._map)
            """)
        hits = [f for f in res.findings if f.invariant == "guarded-by-race"]
        assert hits and "read-write" in hits[0].message, res.pretty()

    def test_volatile_read_idiom_stays_legal(self, tmp_path):
        """TWO lock-free reads break unanimity — the codebase's own
        convention declares the snapshot-read idiom legal here."""
        res = _run_on(tmp_path, "cache/plane.py", """\
            import threading

            class Plane:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._map = {}
                    self._t = threading.Thread(target=self._loop, daemon=True)

                def _loop(self):
                    with self._lock:
                        self._map["k"] = 1
                    with self._lock:
                        self._map["j"] = 2

                def snapshot(self):
                    return dict(self._map)

                def peek(self):
                    return len(self._map)
            """)
        assert res.clean, res.pretty()

    def test_offlock_write_inside_spawned_closure(self, tmp_path):
        """The hedge-leg shape: a closure handed to Thread runs OFF the
        spawning frame's locks — an off-lock write inside it races the
        guarded writes (review finding: the nested-def skip must not
        blind the checker to spawned closures)."""
        res = _run_on(tmp_path, "server/hedge.py", """\
            import threading

            class Hedger:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._winner = {}
                    self._t = threading.Thread(target=self._loop, daemon=True)

                def _loop(self):
                    with self._lock:
                        self._winner["w"] = 1
                    with self._lock:
                        self._winner["v"] = 2

                def race(self):
                    def leg():
                        self._winner["x"] = 3

                    t = threading.Thread(target=leg, daemon=True)
                    t.start()
            """)
        hits = [f for f in res.findings if f.invariant == "guarded-by-race"]
        assert hits, res.pretty()

    def test_inline_closure_under_lock_stays_clean(self, tmp_path):
        """A closure called INLINE (sort key, local helper) runs on the
        caller's thread under the caller's locks — only spawned
        closures get the empty held set."""
        res = _run_on(tmp_path, "server/sorter.py", """\
            import threading

            class Sorter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._rows = {}
                    self._t = threading.Thread(target=self._loop, daemon=True)
                    self._u = threading.Thread(target=self._loop2, daemon=True)

                def _loop(self):
                    with self._lock:
                        def bump():
                            self._rows["a"] = 1

                        bump()

                def _loop2(self):
                    with self._lock:
                        self._rows["b"] = 2
            """)
        assert res.clean, res.pretty()

    def test_threadsafe_containers_exempt(self, tmp_path):
        """Queue/Event attributes are internally synchronized — method
        calls on them from any thread are not races."""
        res = _run_on(tmp_path, "cache/plane.py", """\
            import queue
            import threading

            class Plane:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()
                    self._evt = threading.Event()
                    self._t = threading.Thread(target=self._loop, daemon=True)

                def _loop(self):
                    with self._lock:
                        self._q.put_nowait(1)
                    self._evt.set()

                def submit(self):
                    self._q.put_nowait(2)
                    self._evt.clear()
            """)
        assert res.clean, res.pretty()


class TestThreadMapLive:
    def test_declared_roots_still_resolve(self):
        """Same rot-guard as the hot-path entry points: a rename that
        silently dropped a declared root would hollow out the
        concurrency plane while everything stayed green."""
        from radixmesh_tpu.analysis import tree_index
        from radixmesh_tpu.analysis.thread_roots import DECLARED_ROOTS
        from radixmesh_tpu.analysis.callgraph import get_callgraph

        cg = get_callgraph(tree_index())
        for rel, qual, name, _multi in DECLARED_ROOTS:
            assert (rel, qual) in cg.funcs, f"declared root {name} vanished"

    def test_product_tree_thread_map_is_complete(self):
        """The documented long-lived threads all resolve as roots, and
        the map is finding-free (every target resolved, every spawn
        daemon=True)."""
        from radixmesh_tpu.analysis import check_tree, get_thread_map, tree_index

        assert not [
            f for f in check_tree().findings
            if f.invariant in ("thread-target-unresolved", "thread-daemonless")
        ]
        names = {r.name for r in get_thread_map(tree_index()).roots}
        for expected in (
            "mesh-sender", "mesh-owner-sender", "mesh-ticker", "mesh-gc",
            "mesh-housekeeper", "kv-transfer", "repair-plane",
            "lifecycle-plane", "lifecycle-drain", "engine-runner",
            "wire-receive", "engine-loop", "fleet-aggregator",
        ):
            assert expected in names, f"thread root {expected!r} vanished"
        # Per-connection concurrency is modeled: the HTTP handlers and
        # the wire receive path are multi-instance roots.
        tm = get_thread_map(tree_index())
        assert tm.is_multi("wire-receive")
        assert any(r.kind == "handler" and r.multi for r in tm.roots)

    def test_nested_def_target_maps_to_enclosing(self, tmp_path):
        """A closure handed to Thread (the hedge-leg shape) resolves to
        its enclosing frame instead of escaping the map."""
        res = _run_on(tmp_path, "server/hedge.py", """\
            import threading

            class Hedger:
                def race(self):
                    def leg():
                        return 1

                    t = threading.Thread(target=leg, daemon=True)
                    t.start()
            """)
        assert not [
            f for f in res.findings
            if f.invariant == "thread-target-unresolved"
        ], res.pretty()


class TestProtocolLive:
    def test_dispatch_with_else_is_exhaustive(self, tmp_path):
        res = _run_on(tmp_path, "engine/engine.py", """\
            from .request import RequestState

            class Engine:
                def poll(self, req):
                    if req.state is RequestState.QUEUED:
                        return "wait"
                    elif req.state is RequestState.RUNNING:
                        return "go"
                    else:
                        return "done"
            """)
        # No engine/request.py in this tree: the spec module is absent,
        # so nothing fires either way — exhaustiveness needs the enum.
        assert res.clean, res.pretty()

    def test_declared_transition_stays_legal(self, tmp_path):
        (tmp_path / "engine").mkdir(parents=True, exist_ok=True)
        (tmp_path / "engine" / "request.py").write_text(textwrap.dedent("""\
            import enum

            class RequestState(enum.Enum):
                QUEUED = "queued"
                RUNNING = "running"
                FINISHED = "finished"

            VALID_TRANSITIONS = {
                (RequestState.QUEUED, RequestState.RUNNING),
                (RequestState.RUNNING, RequestState.FINISHED),
            }
            """))
        res = _run_on(tmp_path, "engine/engine.py", """\
            from .request import RequestState

            class Engine:
                def finish(self, req):
                    if req.state is RequestState.RUNNING:
                        req.state = RequestState.FINISHED
            """)
        assert res.clean, res.pretty()

    def test_product_request_table_covers_every_live_transition(self):
        """Runtime cross-check of the declared table: every enum member
        participates, FINISHED is terminal, QUEUED is re-enterable
        (preempt + restore-requeue)."""
        from radixmesh_tpu.engine.request import (
            RequestState,
            VALID_TRANSITIONS,
        )

        members = set(RequestState)
        assert {s for s, _ in VALID_TRANSITIONS} == members - {
            RequestState.FINISHED
        }
        assert {d for _, d in VALID_TRANSITIONS} == members
        assert (RequestState.RUNNING, RequestState.QUEUED) in VALID_TRANSITIONS


# ---------------------------------------------------------------------------
# --changed scoping: the per-commit gate
# ---------------------------------------------------------------------------


class TestChangedScope:
    def test_scope_widens_by_reverse_imports(self, tmp_path):
        from radixmesh_tpu.analysis import changed_scope

        (tmp_path / "utils").mkdir()
        (tmp_path / "cache").mkdir()
        (tmp_path / "server").mkdir()
        (tmp_path / "utils" / "base.py").write_text("def f():\n    return 1\n")
        (tmp_path / "cache" / "mid.py").write_text(
            "from radixmesh_tpu.utils.base import f\n"
        )
        (tmp_path / "server" / "top.py").write_text(
            "from radixmesh_tpu.cache.mid import f\n"
        )
        (tmp_path / "server" / "aloof.py").write_text("x = 1\n")
        index = SourceIndex(tmp_path)
        scope = changed_scope(index, ["utils/base.py"])
        # The change widens transitively up the import chain but never
        # touches unrelated modules.
        assert scope == {"utils/base.py", "cache/mid.py", "server/top.py"}
        assert changed_scope(index, ["server/aloof.py"]) == {"server/aloof.py"}
        assert changed_scope(index, ["gone/deleted.py"]) == set()

def test_meshcheck_cli_exit_zero_on_clean_tree():
    proc = subprocess.run(
        [sys.executable, str(_REPO / "scripts" / "meshcheck.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout
    assert "controls tripped" in proc.stdout
    assert "thread roots" in proc.stdout


def test_meshcheck_cli_changed_mode():
    """The per-commit gate: scoped to git-changed files + reverse-import
    dependents, same exit-code contract (0 = clean; a dirty tree in CI
    is clean too, because the full tree is clean)."""
    proc = subprocess.run(
        [sys.executable, str(_REPO / "scripts" / "meshcheck.py"), "--changed"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "scope:" in proc.stdout


def test_meshcheck_cli_changed_refuses_artifact():
    proc = subprocess.run(
        [
            sys.executable, str(_REPO / "scripts" / "meshcheck.py"),
            "--changed", "--write-artifact",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2
    assert "whole tree" in proc.stderr


def test_meshcheck_cli_exit_one_on_findings(tmp_path):
    """The third pinned exit code (the per-PR quick gate's contract:
    0 clean / 1 findings / 2 framework error): a seeded vocabulary
    violation in a --root tree must exit 1 and print the finding."""
    (tmp_path / "obs").mkdir()
    (tmp_path / "obs" / "bad.py").write_text(
        "from radixmesh_tpu.obs.metrics import get_registry\n"
        "c = get_registry().counter('unprefixed_name', 'd')\n"
        "c.inc()\n"
    )
    proc = subprocess.run(
        [
            sys.executable, str(_REPO / "scripts" / "meshcheck.py"),
            "--root", str(tmp_path), "--no-fixtures",
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "metrics-prefix" in proc.stdout


def test_meshcheck_changed_gate_covers_this_pr(tmp_path):
    """Satellite (PR 13): the --changed quick gate IS the per-PR static
    pass — run it exactly as CI would and pin the full exit-code
    contract in one place: clean tree + dirty worktree exits 0, the
    artifact refusal exits 2 (exit 1 is proven by the seeded-finding
    test above)."""
    proc = subprocess.run(
        [sys.executable, str(_REPO / "scripts" / "meshcheck.py"),
         "--changed"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout
    refused = subprocess.run(
        [sys.executable, str(_REPO / "scripts" / "meshcheck.py"),
         "--changed", "--write-artifact"],
        capture_output=True, text=True, timeout=120,
    )
    assert refused.returncode == 2
