"""Whole-system multi-host rehearsal (VERDICT round-2 weak #7: "nothing
exercises distributed init + the cache ring + serving together across OS
processes — the closest this environment can get to a pod topology").

Two OS processes ("hosts") each run all three planes concurrently:
``jax.distributed`` membership in one global 8-device mesh (compute),
MeshCache ring nodes over the native C++ TCP transport (control), and a
tp=2 serving engine on local devices publishing into the ring (serving).
Cross-host assertions: ring replication both directions, router
attribution, a global-mesh train step with the ring live underneath, and
a post-collectives cache hit on a pre-train prefix. See
``tests/multihost_serving_worker.py`` for the per-host flow."""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def test_multihost_ring_plus_serving_plus_global_train():
    coord, p0, d0, r0 = _free_ports(4)
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="",  # worker sets its own per-process device count
    )
    procs = [
        subprocess.Popen(
            [
                sys.executable, os.path.join(REPO, "tests",
                                             "multihost_serving_worker.py"),
                "--coordinator", f"127.0.0.1:{coord}",
                "--process-id", str(i),
                "--p0", f"127.0.0.1:{p0}",
                "--d0", f"127.0.0.1:{d0}",
                "--r0", f"127.0.0.1:{r0}",
            ],
            cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multihost serving rehearsal hung")
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"host {i} rc={p.returncode}:\n{out[-3000:]}"
    assert "served A" in outs[0] and "saw B via ring" in outs[0]
    assert "saw A via ring" in outs[1] and "served B" in outs[1]
    assert "post-train cache hit ok" in outs[0]
    for i, out in enumerate(outs):
        assert "global train step loss=" in out, out[-1500:]
        assert "WORKER-OK" in out, out[-1500:]
    # Cross-process collectives computed the SAME loss on both hosts.
    l0 = outs[0].split("global train step loss=")[1].split()[0]
    l1 = outs[1].split("global train step loss=")[1].split()[0]
    assert l0 == l1, (l0, l1)
