"""End-to-end real-machinery serving seam at tiny scale (VERDICT round-4
missing #1): scripts/make_real_ckpt.py writes a REAL transformers
checkpoint + a REAL trained BPE tokenizer; the serving stack loads both
through the production paths (models/hf_io.py, server/tokenizer.py) and
serves a TEXT workload — nothing stubbed, every format genuine."""

import sys
import os

import numpy as np
import pytest

jax = pytest.importorskip("jax")

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts"))


@pytest.fixture(scope="module")
def ckpt_dir(tmp_path_factory):
    from make_real_ckpt import save_hf_model, train_tokenizer

    out = str(tmp_path_factory.mktemp("real_ckpt"))
    info = save_hf_model(out, "llama3.2-1b", tiny=True)
    assert info["n_params"] > 0
    train_tokenizer(out, vocab_size=384)
    return out


def test_tokenizer_loads_and_round_trips(ckpt_dir):
    from radixmesh_tpu.server.tokenizer import load_tokenizer

    tok = load_tokenizer(ckpt_dir)
    text = "The cache holds every prefix the router has seen."
    ids = tok.encode(text)
    assert ids and all(isinstance(i, int) for i in ids)
    assert max(ids) < 512  # fits the tiny model's vocab
    # Byte-level BPE round-trips losslessly.
    assert tok.decode(ids) == text


def test_checkpoint_loads_through_hf_io(ckpt_dir):
    import jax.numpy as jnp

    from radixmesh_tpu.models import get_config
    from radixmesh_tpu.models.hf_io import load_hf_checkpoint

    cfg = get_config(
        "llama3.2-1b", hidden=128, n_layers=2, n_heads=4, n_kv_heads=2,
        head_dim=32, intermediate=256, vocab_size=512, dtype=jnp.float32,
    )
    params = load_hf_checkpoint(ckpt_dir, cfg)
    assert params["embed"].shape == (512, 128)
    assert params["layers"]["wq"].shape == (2, 128, 4 * 32)


def test_text_workload_serves_with_prefix_reuse(ckpt_dir):
    import jax.numpy as jnp

    from radixmesh_tpu.engine.engine import Engine
    from radixmesh_tpu.models import get_config
    from radixmesh_tpu.models.hf_io import load_hf_checkpoint
    from radixmesh_tpu.server.tokenizer import load_tokenizer
    from radixmesh_tpu.workload import (
        TextMultiTurnWorkload,
        run_engine_workload,
    )

    cfg = get_config(
        "llama3.2-1b", hidden=128, n_layers=2, n_heads=4, n_kv_heads=2,
        head_dim=32, intermediate=256, vocab_size=512, dtype=jnp.float32,
        max_seq_len=2048,
    )
    params = load_hf_checkpoint(ckpt_dir, cfg)
    tok = load_tokenizer(ckpt_dir)
    engine = Engine(
        cfg, params, num_slots=4096, page_size=4, max_batch=4,
        max_seq_len=1024,
    )
    wl = TextMultiTurnWorkload(
        tok, n_conversations=3, n_turns=3, system_sentences=3,
        user_sentences=2, gen_len=4, seed=0,
    )
    ns = run_engine_workload(engine, wl)
    assert ns["requests"] == 9
    # Turn 2+ reuses each conversation's context through the radix cache.
    assert ns["hit_rate"] > 0.3
    assert ns["reuse_efficiency"] > 0.5
    # The decoded replies are real text through the real tokenizer.
    reply_text = tok.decode(wl.conversations[0].context)
    assert isinstance(reply_text, str) and len(reply_text) > 0
