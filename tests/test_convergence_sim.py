"""Deterministic-seed simulation of eventual-consistency edge cases.

SURVEY §7 hard part (d): the reference under-tests its convergence story —
multi-writer conflicts are only exercised in two hand-picked scenarios
(``correctness.py:137-211``). Replication around the ring delivers every
node the same *multiset* of INSERT oplogs in a node-dependent *order*
(each node sees its own insert first), so the correctness claim is really:
applying the same op multiset in any order yields the same tree. These
tests check that property directly with seeded random workloads:

- ``TestOrderPermutation`` drives ``MeshCache._mesh_insert`` (the exact
  code path both local inserts and remote oplogs take, incl. the conflict
  resolver and dup bookkeeping) with random op sets in many random orders
  and asserts bit-identical convergence + idempotent re-delivery.
- ``TestRandomStorm`` runs seeded multi-writer storms over a live in-proc
  cluster and asserts every replica and the router agree.
"""

import time

import numpy as np
import pytest

from radixmesh_tpu.cache.kv_pool import PagedKVPool
from radixmesh_tpu.cache.mesh_cache import MeshCache
from radixmesh_tpu.cache.mesh_values import PrefillValue
from radixmesh_tpu.comm.inproc import InprocHub
from radixmesh_tpu.config import MeshConfig, NodeRole

pytestmark = pytest.mark.quick


def wait_for(pred, timeout=15.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


def random_ops(rng: np.random.Generator, n_ops: int, n_writers: int):
    """A conflict-heavy op multiset: keys are random-length prefixes of a
    few base chains plus occasional random suffixes, so inserts nest,
    overlap, split existing nodes, and collide across writers."""
    chains = [
        rng.integers(0, 8, size=rng.integers(4, 12)).astype(np.int32)
        for _ in range(3)
    ]
    ops = []
    for i in range(n_ops):
        chain = chains[rng.integers(0, len(chains))]
        cut = int(rng.integers(1, len(chain) + 1))
        key = chain[:cut]
        if rng.random() < 0.3:  # branch off with a fresh suffix
            key = np.concatenate(
                [key, rng.integers(8, 16, size=rng.integers(1, 4)).astype(np.int32)]
            )
        rank = int(rng.integers(0, n_writers))
        # Indices are origin-deterministic: the same (key, rank) always
        # carries the same indices, as on a real node re-advertising the
        # same cached prefix.
        base = rank * 10_000 + int(key[0]) * 100
        indices = (base + np.arange(len(key))).astype(np.int32)
        ops.append((key, rank, indices))
    return ops


def random_paged_ops(rng: np.random.Generator, n_ops: int, n_writers: int,
                     page: int):
    """``random_ops`` at page granularity: keys are page-multiples built
    from unit chains (each unit expands to ``page`` tokens) and indices
    are page-aligned contiguous runs — the engine's paged-allocator
    invariant that page-granular replication requires."""
    chains = [
        rng.integers(0, 8, size=rng.integers(2, 6)).astype(np.int32)
        for _ in range(3)
    ]
    ops = []
    for _ in range(n_ops):
        chain = chains[rng.integers(0, len(chains))]
        cut = int(rng.integers(1, len(chain) + 1))
        units = chain[:cut]
        if rng.random() < 0.3:
            units = np.concatenate(
                [units, rng.integers(8, 16, size=rng.integers(1, 3)).astype(np.int32)]
            )
        key = np.repeat(units, page).astype(np.int32)
        # Unit u's page token i gets token id units[u] — page-multiples by
        # construction. Indices: deterministic page-aligned run per
        # (key, rank), as a node re-advertising the same prefix.
        rank = int(rng.integers(0, n_writers))
        base = (rank * 10_000 + int(units[0]) * 100) // page * page
        indices = (base + np.arange(len(key))).astype(np.int32)
        ops.append((key, rank, indices))
    return ops


def make_unwired_node(
    rank: int = 0, pool: PagedKVPool | None = None, page: int = 1
) -> MeshCache:
    """A MeshCache with transports never opened: ``_mesh_insert`` and the
    conflict/dup machinery are fully functional without ``start()``."""
    prefill = [f"p{i}" for i in range(3)]
    cfg = MeshConfig(
        prefill_nodes=prefill,
        decode_nodes=["d0"],
        router_nodes=[],
        local_addr=prefill[rank],
        protocol="inproc",
        page_size=page,
    )
    return MeshCache(cfg, pool=pool)


def snapshot(node: MeshCache, probe_keys) -> list[tuple]:
    """Observable state per probe key: match length, per-node origin
    ranks, and the concatenated slot indices."""
    out = []
    for key in probe_keys:
        res = node.tree.match_prefix(key, split_partial=False)
        ranks = tuple(v.rank for v in res.values)
        idx = (
            np.concatenate([np.asarray(v) for v in res.values])
            if res.values
            else np.empty(0, np.int32)
        )
        out.append((res.length, ranks, idx.tolist()))
    return out


class TestOrderPermutation:
    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_any_delivery_order_converges(self, seed):
        rng = np.random.default_rng(seed)
        ops = random_ops(rng, n_ops=40, n_writers=3)
        probe_keys = [key for key, _, _ in ops]

        reference_snap = None
        for perm_i in range(6):
            order = rng.permutation(len(ops))
            node = make_unwired_node()
            with node._lock:
                for j in order:
                    key, rank, indices = ops[j]
                    node._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
            snap = snapshot(node, probe_keys)
            if reference_snap is None:
                reference_snap = snap
            else:
                assert snap == reference_snap, (
                    f"seed={seed}: delivery order {perm_i} produced a "
                    f"different tree"
                )

    @pytest.mark.parametrize("seed", [7, 8])
    def test_redelivery_is_idempotent(self, seed):
        rng = np.random.default_rng(seed)
        ops = random_ops(rng, n_ops=30, n_writers=3)
        probe_keys = [key for key, _, _ in ops]

        node = make_unwired_node()
        with node._lock:
            for key, rank, indices in ops:
                node._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
        once = snapshot(node, probe_keys)
        # Ring re-delivery: the same multiset lands a second time (e.g. a
        # rejoined node replays; the reference relies on idempotence,
        # cache_oplog.py docstring). Tree state must be unchanged; dup
        # ENTRIES may legitimately re-key to the current (finer) node
        # granularity — the slot ledger, not the entry set, is what must
        # stay safe (covered by test_slot_safety_*).
        with node._lock:
            for j in rng.permutation(len(ops)):
                key, rank, indices = ops[j]
                node._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
        assert snapshot(node, probe_keys) == once

    def test_lowest_rank_wins_pointwise(self):
        """Against the spec, not another run: after all orders, every
        token position is owned by the LOWEST rank that ever wrote it."""
        rng = np.random.default_rng(42)
        ops = random_ops(rng, n_ops=50, n_writers=4)
        node = make_unwired_node()
        with node._lock:
            for key, rank, indices in ops:
                node._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))

        # Oracle: min rank per exact token-path position.
        min_rank: dict[tuple, int] = {}
        for key, rank, _ in ops:
            for d in range(1, len(key) + 1):
                p = tuple(key[:d].tolist())
                min_rank[p] = min(min_rank.get(p, rank), rank)

        for key, _, _ in ops:
            res = node.tree.match_prefix(key, split_partial=False)
            assert res.length == len(key)
            pos = 0
            for v in res.values:
                for _ in range(len(v)):
                    p = tuple(key[: pos + 1].tolist())
                    assert v.rank == min_rank[p], (
                        f"position {p}: owner rank {v.rank}, expected "
                        f"{min_rank[p]}"
                    )
                    pos += 1


class TestFingerprintConvergence:
    """The fleet-plane convergence audit's core claim, checked at the
    ``_mesh_insert`` layer: the same op MULTISET in any delivery order
    yields the same tree fingerprint on every replica (conflict
    resolution swaps values, never keys — and the fingerprint digests
    the key set); a replica that misses one op fingerprints differently."""

    @pytest.mark.parametrize("seed", [0, 5])
    def test_permuted_delivery_equal_fingerprints(self, seed):
        rng = np.random.default_rng(seed)
        ops = random_ops(rng, n_ops=40, n_writers=3)
        fps = set()
        for _ in range(5):
            node = make_unwired_node()
            with node._lock:
                for j in rng.permutation(len(ops)):
                    key, rank, indices = ops[j]
                    node._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
            fps.add(node.tree.fingerprint_)
        assert len(fps) == 1
        assert fps.pop() != 0

    def test_missing_one_op_diverges_and_redelivery_heals(self):
        rng = np.random.default_rng(9)
        ops = random_ops(rng, n_ops=30, n_writers=3)
        full = make_unwired_node()
        partial = make_unwired_node(rank=1)
        # The dropped op must carry a token path no other op covers, or
        # the fingerprint (a key-SET digest) legitimately matches.
        dropped_key = np.array([77, 78, 79], np.int32)
        dropped = (dropped_key, 0, np.arange(3, dtype=np.int32))
        with full._lock, partial._lock:
            for key, rank, indices in ops + [dropped]:
                full._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
            for key, rank, indices in ops:
                partial._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
        assert full.tree.fingerprint_ != partial.tree.fingerprint_
        # Late (re)delivery of the missing op heals the divergence.
        with partial._lock:
            key, rank, indices = dropped
            partial._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
        assert full.tree.fingerprint_ == partial.tree.fingerprint_

    def test_replicated_delete_keeps_fingerprints_equal(self):
        """DELETE removes leaves through a direct-detach path (not
        _remove_node) — the fingerprint must follow on every replica,
        and land exactly on a tree that never saw the key."""
        k1 = np.arange(8, dtype=np.int32)
        k2 = np.arange(50, 58, dtype=np.int32)
        a, b, never = (
            make_unwired_node(0), make_unwired_node(1), make_unwired_node(2)
        )
        for n in (a, b):
            with n._lock:
                n._mesh_insert(k1.copy(), PrefillValue(np.arange(8, dtype=np.int32), 0))
                n._mesh_insert(k2.copy(), PrefillValue(np.arange(8, dtype=np.int32), 0))
        with never._lock:
            never._mesh_insert(k1.copy(), PrefillValue(np.arange(8, dtype=np.int32), 0))
        with a._lock:
            assert a._apply_delete(k2)
        assert a.tree.fingerprint_ != b.tree.fingerprint_
        with b._lock:
            assert b._apply_delete(k2)
        assert a.tree.fingerprint_ == b.tree.fingerprint_
        assert a.tree.fingerprint_ == never.tree.fingerprint_

    def test_router_replica_fingerprint_matches_pd(self):
        """Router replicas store RouterValues, not slot arrays — the
        fingerprint must still compare equal (it digests keys only)."""
        from radixmesh_tpu.cache.mesh_values import RouterValue

        rng = np.random.default_rng(21)
        ops = random_ops(rng, n_ops=25, n_writers=2)
        pd = make_unwired_node()
        router = MeshCache(
            MeshConfig(
                prefill_nodes=["p0", "p1", "p2"],
                decode_nodes=["d0"],
                router_nodes=["r0"],
                local_addr="r0",
                protocol="inproc",
            )
        )
        with pd._lock, router._lock:
            for key, rank, indices in ops:
                pd._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
                router._mesh_insert(key.copy(), RouterValue(rank, len(key)))
        assert pd.tree.fingerprint_ == router.tree.fingerprint_


class TestRepairSessionSim:
    """The anti-entropy repair protocol at the ``_mesh_insert`` layer
    (``cache/repair_plane.py`` / ``MeshCache.repair_push_keys``), with
    the ring replaced by a captured-oplog pipe: fully deterministic, no
    threads, no clocks. The live-cluster variants are in
    ``tests/test_repair_plane.py``; these pin the *semantics* — what a
    session pushes and what applying it yields."""

    @staticmethod
    def _pipe(src: MeshCache, buckets, exclude_hashes, budget=10_000):
        """Run ``src``'s repair push with ``_broadcast`` captured, and
        return the re-emitted oplogs as WIRE frames (serialize → bytes),
        i.e. exactly what peers would receive."""
        from radixmesh_tpu.cache.oplog import serialize

        captured = []
        orig = src._broadcast
        src._broadcast = lambda op: captured.append(serialize(op))
        try:
            src.repair_push_keys(buckets, exclude_hashes, budget)
        finally:
            src._broadcast = orig
        return captured

    @staticmethod
    def _diff(a: MeshCache, b: MeshCache) -> list[int]:
        return [
            int(i)
            for i in np.nonzero(a.tree.fp_buckets_ != b.tree.fp_buckets_)[0]
        ]

    @staticmethod
    def _hashes(node: MeshCache, buckets) -> set[int]:
        with node._lock:
            return {
                node.tree.path_hash(n)
                for n in node.tree.nodes_touching_buckets(buckets)
            }

    def _session(self, a: MeshCache, b: MeshCache) -> None:
        """One full symmetric repair session a↔b: bucket diff → key
        summaries → each side applies the other's one-sided pushes
        through the REAL receive path (deserialize → oplog_received)."""
        buckets = self._diff(a, b)
        ha, hb = self._hashes(a, buckets), self._hashes(b, buckets)
        for frame in self._pipe(a, buckets, hb):
            b.oplog_received(frame)
        for frame in self._pipe(b, buckets, ha):
            a.oplog_received(frame)

    def test_dropped_insert_healed(self):
        rng = np.random.default_rng(3)
        ops = random_ops(rng, n_ops=25, n_writers=3)
        full, partial = make_unwired_node(0), make_unwired_node(1)
        dropped = (np.array([88, 89], np.int32), 2,
                   np.arange(2, dtype=np.int32))
        with full._lock, partial._lock:
            for key, rank, indices in ops + [dropped]:
                full._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
            for key, rank, indices in ops:
                partial._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
        assert full.tree.fingerprint_ != partial.tree.fingerprint_
        self._session(full, partial)
        assert full.tree.fingerprint_ == partial.tree.fingerprint_
        assert (full.tree.fp_buckets_ == partial.tree.fp_buckets_).all()
        res = partial.tree.match_prefix(dropped[0], split_partial=False)
        assert res.length == 2 and all(v.rank == 2 for v in res.values)

    def test_dropped_delete_healed_by_resurrection(self):
        """DELETE lost to one replica: the session converges the pair on
        the union (the keeper re-replicates; tombstone-free heal)."""
        k1, k2 = np.arange(6, dtype=np.int32), np.arange(30, 36, dtype=np.int32)
        a, b = make_unwired_node(0), make_unwired_node(1)
        for n in (a, b):
            with n._lock:
                n._mesh_insert(k1.copy(), PrefillValue(np.arange(6, dtype=np.int32), 0))
                n._mesh_insert(k2.copy(), PrefillValue(np.arange(6, dtype=np.int32), 0))
        with a._lock:
            assert a._apply_delete(k2)  # b's copy of the DELETE dropped
        assert a.tree.fingerprint_ != b.tree.fingerprint_
        self._session(a, b)
        assert a.tree.fingerprint_ == b.tree.fingerprint_
        assert a.tree.match_prefix(k2, split_partial=False).length == len(k2)

    def test_asymmetric_partition_healed(self):
        """Each side missed a DIFFERENT slice of the op stream (the
        one-way-partition outcome): one symmetric session converges both
        to the union with correct per-position owners."""
        rng = np.random.default_rng(17)
        ops = random_ops(rng, n_ops=40, n_writers=3)
        third = len(ops) // 3
        a, b = make_unwired_node(0), make_unwired_node(1)
        with a._lock, b._lock:
            for key, rank, indices in ops[: 2 * third]:  # a missed the tail
                a._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
            for key, rank, indices in ops[third:]:  # b missed the head
                b._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
        assert a.tree.fingerprint_ != b.tree.fingerprint_
        self._session(a, b)
        # The repair contract is KEY-SET convergence (the fingerprint is
        # deliberately value-blind): both sides hold the union and match
        # every op's full key. Per-position value OWNERS may still
        # differ on paths both sides already held (each resolved against
        # the multiset it actually saw) — the same tolerated zone as
        # live cross-origin races (mesh_cache.py consistency model).
        assert a.tree.fingerprint_ == b.tree.fingerprint_
        assert (a.tree.fp_buckets_ == b.tree.fp_buckets_).all()
        writers: dict[tuple, set] = {}
        for key, rank, _ in ops:
            for d in range(1, len(key) + 1):
                writers.setdefault(tuple(key[:d].tolist()), set()).add(rank)
        for node in (a, b):
            for key, _, _ in ops:
                res = node.tree.match_prefix(key, split_partial=False)
                assert res.length == len(key), "union key missing post-repair"
                pos = 0
                for v in res.values:
                    for _ in range(len(v)):
                        p = tuple(key[: pos + 1].tolist())
                        # Every owner is a REAL writer of that position —
                        # repair can never fabricate ownership.
                        assert v.rank in writers[p]
                        pos += 1

    def test_conflict_winners_unchanged_post_repair(self):
        """Repair pushes ride the normal conflict-resolution path, so
        the lowest-writing-rank-wins oracle must hold pointwise AFTER a
        heal exactly as it does after live replication."""
        rng = np.random.default_rng(29)
        ops = random_ops(rng, n_ops=40, n_writers=4)
        a, b = make_unwired_node(0), make_unwired_node(1)
        drop_at_b = {5, 11, 23, 31}  # b missed these (conflict-heavy set)
        with a._lock, b._lock:
            for i, (key, rank, indices) in enumerate(ops):
                a._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
                if i not in drop_at_b:
                    b._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
        self._session(a, b)
        assert a.tree.fingerprint_ == b.tree.fingerprint_
        min_rank: dict[tuple, int] = {}
        for key, rank, _ in ops:
            for d in range(1, len(key) + 1):
                p = tuple(key[:d].tolist())
                min_rank[p] = min(min_rank.get(p, rank), rank)
        for node in (a, b):
            for key, _, _ in ops:
                res = node.tree.match_prefix(key, split_partial=False)
                assert res.length == len(key)
                pos = 0
                for v in res.values:
                    for _ in range(len(v)):
                        p = tuple(key[: pos + 1].tolist())
                        assert v.rank == min_rank[p], (
                            f"post-repair owner drift at {p}: "
                            f"{v.rank} != {min_rank[p]}"
                        )
                        pos += 1

    def test_session_is_idempotent(self):
        """Re-running a session against converged replicas pushes
        nothing and changes nothing (quiescence at the protocol layer)."""
        rng = np.random.default_rng(41)
        ops = random_ops(rng, n_ops=20, n_writers=2)
        a, b = make_unwired_node(0), make_unwired_node(1)
        with a._lock, b._lock:
            for key, rank, indices in ops:
                a._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
            for key, rank, indices in ops[:-1]:
                b._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
        self._session(a, b)
        assert a.tree.fingerprint_ == b.tree.fingerprint_
        fp = a.tree.fingerprint_
        buckets = self._diff(a, b)
        assert buckets == []
        assert self._pipe(a, buckets, set()) == []
        self._session(a, b)  # full re-run: still a no-op
        assert a.tree.fingerprint_ == fp == b.tree.fingerprint_


class TestDupSlotSafety:
    """The dup-GC slot ledger under granularity drift.

    Dup entries are keyed by the conflicted node's token path, and node
    boundaries move as later inserts split nodes — so re-delivery records
    the same losing slot under entries of different granularity. Freeing
    per-entry index arrays directly double-frees (the bug these tests
    pinned before ``MeshCache._dup_pending`` existed)."""

    def test_granularity_drift_regression(self):
        pool = PagedKVPool(num_slots=64, num_layers=1, num_kv_heads=1, head_dim=2)
        node = make_unwired_node(rank=2, pool=pool)
        slots = pool.alloc(2)  # rank-2's real KV for key [3, 7]
        from radixmesh_tpu.cache.oplog import GCEntry

        with node._lock:
            # rank2 writes [3,7]; rank0's conflicting copy wins everywhere.
            node._mesh_insert(np.array([3, 7], np.int32), PrefillValue(slots, 2))
            node._mesh_insert(
                np.array([3, 7], np.int32), PrefillValue(np.array([90, 91]), 0)
            )
            # rank1 writes the shorter prefix — splits the winning node.
            node._mesh_insert(
                np.array([3], np.int32), PrefillValue(np.array([80]), 1)
            )
            # Ring re-delivery of rank2's original op now conflicts at BOTH
            # split nodes, recording overlapping-by-position losers.
            node._mesh_insert(np.array([3, 7], np.int32), PrefillValue(slots, 2))
            # Unanimous GC of every entry must free {slots} exactly once.
            free_before = pool.free_slots
            for nk in list(node.dup_nodes):
                node._gc_collect(
                    GCEntry(np.asarray(nk.tokens, np.int32), nk.value_rank, 99)
                )
            assert not node._dup_pending
            assert pool.free_slots == free_before + len(slots)
            assert not pool.allocator.is_allocated(slots).any()

    @pytest.mark.parametrize("seed", [3, 13])
    def test_storm_redelivery_splits_gc_never_corrupts(self, seed):
        rng = np.random.default_rng(seed)
        pool = PagedKVPool(num_slots=1024, num_layers=1, num_kv_heads=1, head_dim=2)
        my_rank = 2
        node = make_unwired_node(rank=my_rank, pool=pool)
        from radixmesh_tpu.cache.oplog import GCEntry

        # Base chains; rank-2 ops reuse REAL pool slots per chain position
        # (prefix reuse: the same token position always maps to the same
        # slot, as an engine republishing its cache does).
        chains = [
            rng.integers(0, 6, size=rng.integers(4, 10)).astype(np.int32)
            for _ in range(3)
        ]
        chain_slots = [pool.alloc(len(c)) for c in chains]
        ops = []
        for _ in range(40):
            ci = int(rng.integers(0, len(chains)))
            cut = int(rng.integers(1, len(chains[ci]) + 1))
            rank = int(rng.integers(0, 3))
            key = chains[ci][:cut]
            if rank == my_rank:
                indices = chain_slots[ci][:cut]
            else:
                indices = (rank * 10_000 + np.arange(cut)).astype(np.int32)
            ops.append((key, rank, indices))

        with node._lock:
            for key, rank, indices in ops:
                node._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
            for j in rng.permutation(len(ops)):  # ring re-delivery
                key, rank, indices = ops[j]
                node._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
            # Unanimous GC across all entries: must never raise (a double
            # free raises ValueError in SlotAllocator.free).
            for nk in list(node.dup_nodes):
                node._gc_collect(
                    GCEntry(np.asarray(nk.tokens, np.int32), nk.value_rank, 99)
                )
            assert not node._dup_pending

            # Nothing the tree still references was freed.
            for tn in node.tree._all_nodes():
                v = tn.value
                if isinstance(v, PrefillValue) and v.rank == my_rank and len(v):
                    assert pool.allocator.is_allocated(v.indices).all(), (
                        f"seed={seed}: GC freed slots the tree references"
                    )


def make_storm_cluster(n_prefill=3, n_decode=2, num_slots=512, page=1):
    """Start a full in-proc cluster (P/D ring + router), wait for the
    startup barrier, and return ``(all_nodes, ring_nodes, router)``."""
    prefill = [f"p{i}" for i in range(n_prefill)]
    decode = [f"d{i}" for i in range(n_decode)]
    nodes: list[MeshCache] = []
    for addr in prefill + decode + ["r0"]:
        cfg = MeshConfig(
            prefill_nodes=prefill,
            decode_nodes=decode,
            router_nodes=["r0"],
            local_addr=addr,
            protocol="inproc",
            tick_interval_s=0.05,
            gc_interval_s=30.0,
            page_size=page,
        )
        pool = (
            None
            if cfg.local_role is NodeRole.ROUTER
            else PagedKVPool(
                num_slots=num_slots, num_layers=1, num_kv_heads=1, head_dim=2,
                page_size=page,
            )
        )
        nodes.append(MeshCache(cfg, pool=pool))
    for n in nodes:
        n.start()
    for n in nodes:
        assert n.wait_ready(timeout=10)
    ring = [n for n in nodes if n.role is not NodeRole.ROUTER]
    return nodes, ring, nodes[-1]


@pytest.fixture(autouse=True)
def fresh_hub():
    InprocHub.reset_default()
    yield
    InprocHub.reset_default()


class TestRandomStorm:
    @pytest.mark.parametrize("seed", [11, 23])
    def test_storm_converges_everywhere(self, seed):
        rng = np.random.default_rng(seed)
        nodes, ring, router = make_storm_cluster()
        try:
            prefill = [f"p{i}" for i in range(3)]
            ops = random_ops(rng, n_ops=25, n_writers=len(ring))
            for key, rank, _ in ops:
                writer = ring[rank]
                slots = writer.pool.alloc(len(key))
                assert slots is not None
                writer.insert(key, slots)
                if rng.random() < 0.3:
                    time.sleep(0.01)  # vary interleave with ring forwarding

            probe_keys = [key for key, _, _ in ops]

            def converged():
                snaps = [
                    [
                        (r.length, tuple(v.rank for v in r.values))
                        for r in (
                            n.tree.match_prefix(k, split_partial=False)
                            for k in probe_keys
                        )
                    ]
                    for n in ring
                ]
                return all(s == snaps[0] for s in snaps[1:])

            assert wait_for(converged), f"seed={seed}: replicas diverged"

            # Router attribution agrees with the ring consensus: for each
            # probe key the advertised prefill rank is the owner of the
            # deepest matched node on any replica.
            for key in probe_keys:
                res = ring[0].tree.match_prefix(key, split_partial=False)
                want_ranks = {v.rank for v in res.values}
                route = router.match_prefix(key)
                assert route.match_len == res.length
                prefill_ranks = {
                    v.rank for v in res.values if v.rank < len(prefill)
                }
                if prefill_ranks:
                    assert route.prefill_rank in prefill_ranks
        finally:
            for n in nodes:
                n.close()


class TestDeleteResetStorm:
    """DELETE/RESET racing INSERT across the ring. Cross-origin
    delete/insert races are deliberately tolerated (cache semantics — see
    mesh_cache.py module docstring), so the invariants here are safety,
    not convergence: no node crashes, allocators stay consistent, and the
    ring still replicates fresh inserts afterwards."""

    @pytest.mark.parametrize("seed", [5, 17])
    def test_mixed_op_storm_stays_safe(self, seed):
        rng = np.random.default_rng(seed)
        nodes, ring, router = make_storm_cluster()
        try:
            keys: list[np.ndarray] = []
            for _ in range(50):
                node = ring[rng.integers(0, len(ring))]
                roll = rng.random()
                if roll < 0.55 or not keys:
                    key = rng.integers(0, 9, size=rng.integers(2, 6)).astype(
                        np.int32
                    )
                    slots = node.pool.alloc(len(key))
                    if slots is not None:
                        node.insert(key, slots)
                        keys.append(key)
                elif roll < 0.85:
                    node.delete(keys[rng.integers(0, len(keys))])
                else:
                    node.reset_all()
                    keys.clear()
                if rng.random() < 0.3:
                    time.sleep(0.01)
            time.sleep(1.0)

            # Safety: fresh insert still replicates everywhere + routes.
            writer = ring[0]
            key = np.array([7, seed % 9, 7], dtype=np.int32)
            slots = writer.pool.alloc(len(key))
            assert slots is not None
            writer.insert(key, slots)
            assert wait_for(
                lambda: all(
                    n.tree.match_prefix(key, split_partial=False).length
                    == len(key)
                    for n in ring
                )
            ), "post-storm insert did not replicate"
            assert wait_for(
                lambda: router.match_prefix(key).match_len == len(key)
            ), "router replica wedged after DELETE/RESET storm"
            # Allocator safety on every node: self-rank tree values must
            # reference live slots (DELETE/RESET freed correctly, never
            # slots the tree still holds).
            for n in ring:
                n.run_gc_round()
            time.sleep(1.0)
            for n in ring:
                for tn in n.tree._all_nodes():
                    v = tn.value
                    if (
                        isinstance(v, PrefillValue)
                        and v.rank == n.rank
                        and len(v)
                    ):
                        assert n.pool.allocator.is_allocated(v.indices).all()
        finally:
            for n in nodes:
                n.close()


class TestPageGranular:
    """Page-granular replication (VERDICT round-3 next-step #4): the mesh
    tree at page_size=16, INSERT oplogs shipping one page id per 16
    tokens, expanded back to slots on receive. The convergence properties
    must be exactly the token-granularity ones."""

    PAGE = 16

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_any_delivery_order_converges(self, seed):
        rng = np.random.default_rng(seed)
        ops = random_paged_ops(rng, n_ops=40, n_writers=3, page=self.PAGE)
        probe_keys = [key for key, _, _ in ops]

        reference_snap = None
        for perm_i in range(6):
            order = rng.permutation(len(ops))
            node = make_unwired_node(page=self.PAGE)
            with node._lock:
                for j in order:
                    key, rank, indices = ops[j]
                    node._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
            snap = snapshot(node, probe_keys)
            if reference_snap is None:
                reference_snap = snap
            else:
                assert snap == reference_snap, (
                    f"seed={seed}: delivery order {perm_i} diverged at "
                    f"page={self.PAGE}"
                )

    @pytest.mark.parametrize("seed", [7, 8])
    def test_redelivery_is_idempotent(self, seed):
        rng = np.random.default_rng(seed)
        ops = random_paged_ops(rng, n_ops=30, n_writers=3, page=self.PAGE)
        probe_keys = [key for key, _, _ in ops]
        node = make_unwired_node(page=self.PAGE)
        with node._lock:
            for key, rank, indices in ops:
                node._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
        once = snapshot(node, probe_keys)
        with node._lock:
            for j in rng.permutation(len(ops)):
                key, rank, indices = ops[j]
                node._mesh_insert(key.copy(), PrefillValue(indices.copy(), rank))
        assert snapshot(node, probe_keys) == once

    def test_insert_validates_contiguity_and_floors(self):
        """Origin-side guards: non-page-contiguous slots fail loudly; a
        sub-page tail is floored off the publish."""
        page = 4
        pool = PagedKVPool(
            num_slots=64, num_layers=1, num_kv_heads=1, head_dim=2,
            page_size=page,
        )
        node = make_unwired_node(pool=pool, page=page)
        scattered = np.asarray([0, 1, 2, 5], np.int32)  # breaks page 0
        with pytest.raises(ValueError, match="page-contiguous"):
            node.insert(np.arange(4, dtype=np.int32), scattered)
        # 6 tokens at page 4 → only the first page publishes.
        slots = pool.alloc(6)
        got = node.insert(np.asarray([1, 1, 1, 1, 2, 2], np.int32), slots)
        assert got == 0  # nothing previously cached
        assert node.match_prefix([1, 1, 1, 1, 2, 2]).length == page

    @pytest.mark.parametrize("seed", [31, 47])
    def test_storm_converges_over_the_wire(self, seed):
        """Live in-proc cluster at page=16: oplogs serialize page ids
        (wire v3) and every replica expands them back to the SAME slot
        runs the writer advertised — convergence including indices, not
        just lengths/ranks."""
        rng = np.random.default_rng(seed)
        nodes, ring, router = make_storm_cluster(
            num_slots=2048, page=self.PAGE
        )
        try:
            ops = []
            chains = [
                rng.integers(0, 6, size=rng.integers(1, 4)).astype(np.int32)
                for _ in range(3)
            ]
            chain_slots: dict[tuple, np.ndarray] = {}
            for _ in range(20):
                ci = int(rng.integers(0, len(chains)))
                cut = int(rng.integers(1, len(chains[ci]) + 1))
                rank = int(rng.integers(0, len(ring)))
                key = np.repeat(chains[ci][:cut], self.PAGE).astype(np.int32)
                ck = (rank, ci, cut)
                if ck not in chain_slots:
                    slots = ring[rank].pool.alloc(len(key))
                    assert slots is not None
                    chain_slots[ck] = slots
                ring[rank].insert(key, chain_slots[ck])
                ops.append((key, rank, chain_slots[ck]))

            probe_keys = [key for key, _, _ in ops]

            def converged():
                snaps = [snapshot(n, probe_keys) for n in ring]
                return all(s == snaps[0] for s in snaps[1:])

            assert wait_for(converged), f"seed={seed}: replicas diverged"
            # Router sees lengths (RouterValues carry no indices).
            for key, _, _ in ops:
                assert router.match_prefix(key).match_len == len(key)
            # Every replica's matched indices expand to real slot runs of
            # the winning writer — page expansion reproduced the origin's
            # advertisement bit-for-bit.
            res = ring[1].tree.match_prefix(probe_keys[0], split_partial=False)
            assert res.length == len(probe_keys[0])
            for v in res.values:
                assert len(v) % self.PAGE == 0
                run = np.asarray(v.indices)
                by_page = run.reshape(-1, self.PAGE)
                assert (
                    by_page
                    == by_page[:, :1] + np.arange(self.PAGE, dtype=np.int32)
                ).all()
        finally:
            for n in nodes:
                n.close()

    def test_gc_frees_loser_slots_at_page_granularity(self):
        """Conflicting page-aligned writes: the losing writer's whole
        page run returns to its pool after a unanimous GC round."""
        page = self.PAGE
        nodes, ring, router = make_storm_cluster(num_slots=2048, page=page)
        try:
            key = np.repeat(np.asarray([9, 8], np.int32), page)
            winner, loser = ring[0], ring[2]
            ws = winner.pool.alloc(len(key))
            winner.insert(key, ws)
            ls = loser.pool.alloc(len(key))
            loser.insert(key, ls)
            from radixmesh_tpu.cache.oplog import NodeKey

            nk = NodeKey(key, loser.rank)
            assert wait_for(
                lambda: all(nk in n.dup_nodes for n in ring)
            ), "duplicate never recorded everywhere"
            free_before = loser.pool.free_slots
            loser.run_gc_round()
            assert wait_for(
                lambda: loser.pool.free_slots == free_before + len(key)
            ), "loser's page-granular duplicate slots never freed"
            assert all(
                v.rank == winner.rank
                for v in loser.match_prefix(key).values
            )
        finally:
            for n in nodes:
                n.close()
