"""Full-stack E2E over OS processes + real HTTP + native TCP ring.

The deployment the reference implies but never ships (its entry points
stop at cache correctness, ``README.md:33-45``): ``launch.py node`` runs
prefill/decode SERVING nodes (Engine + advertisement-only MeshCache over
one pool) and a router node with the routing API. A client serves a
request on the routed node, the publish replicates, and a shared-prefix
follow-up routes back to that node and hits its cache.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

SERVE_OFFSET = 1000


def _free_port_pairs(n, offset=SERVE_OFFSET):
    """n ports whose +offset siblings are also free."""
    out = []
    while len(out) < n:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        if port + offset > 65535:
            continue
        try:
            s2 = socket.socket()
            s2.bind(("127.0.0.1", port + offset))
            s2.close()
        except OSError:
            continue
        out.append(port)
    return out


def _post(url, obj, timeout=60.0):
    req = urllib.request.Request(
        url,
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def _wait_http(url, timeout=90.0):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            _get(url, timeout=2.0)
            return
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.25)
    raise TimeoutError(f"{url} never came up: {last}")


@pytest.fixture
def cluster(tmp_path):
    p_port, d_port, r_port, r_http = _free_port_pairs(4)
    prefill = [f"127.0.0.1:{p_port}"]
    decode = [f"127.0.0.1:{d_port}"]
    router = [f"127.0.0.1:{r_port}"]
    base = {
        "prefill_nodes": prefill,
        "decode_nodes": decode,
        "router_nodes": router,
        "protocol": "tcp",
        "tick_interval_s": 0.2,
        "gc_interval_s": 60.0,
        "serve_port_offset": SERVE_OFFSET,
        "model": {
            "preset": "llama3-tiny",
            "page_size": 4,
            "kv_slots": 1024,
            "max_batch": 4,
        },
    }
    procs = []
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    for addr in prefill + decode + router:
        cfg = dict(base, local_addr=addr)
        path = tmp_path / f"{addr.replace(':', '_')}.yaml"
        path.write_text(json.dumps(cfg))  # JSON is valid YAML
        cmd = [
            sys.executable, "-m", "radixmesh_tpu.launch", "node",
            "--config-file", str(path),
        ]
        if addr in router:
            cmd += ["--http-port", str(r_http)]
        procs.append(subprocess.Popen(cmd, env=env))
    urls = {
        "prefill": f"http://127.0.0.1:{p_port + SERVE_OFFSET}",
        "decode": f"http://127.0.0.1:{d_port + SERVE_OFFSET}",
        "router": f"http://127.0.0.1:{r_http}",
    }
    try:
        for u in urls.values():
            _wait_http(u + "/healthz")
        yield urls
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()


def test_route_then_serve_hits_cache(cluster):
    prompt = list(range(1, 25))  # 24 tokens

    # Cold: route, then serve on the routed prefill node.
    r1 = _post(cluster["router"] + "/route", {"input_ids": prompt})
    assert r1["prefill_serve_addr"] is not None
    serve_url = "http://" + r1["prefill_serve_addr"]
    assert serve_url in (cluster["prefill"], cluster["decode"], serve_url)
    g1 = _post(
        serve_url + "/generate",
        {"input_ids": prompt, "max_tokens": 4, "temperature": 0.0},
        timeout=120.0,
    )
    assert len(g1["output_ids"]) == 4
    assert g1["cached_tokens"] == 0

    # The publish replicates; the router must learn it and route the
    # shared-prefix follow-up to the SAME node, as a cache hit.
    follow = prompt + [100, 101, 102]
    deadline = time.monotonic() + 30
    r2 = None
    while time.monotonic() < deadline:
        r2 = _post(cluster["router"] + "/route", {"input_ids": follow})
        if r2["prefill_cache_hit"]:
            break
        time.sleep(0.25)
    assert r2 and r2["prefill_cache_hit"], f"router never saw the prefix: {r2}"
    assert "http://" + r2["prefill_serve_addr"] == serve_url
    assert r2["match_len"] >= len(prompt)

    # Serving the follow-up on the routed node is a prefix hit.
    g2 = _post(
        serve_url + "/generate",
        {"input_ids": follow, "max_tokens": 4, "temperature": 0.0},
        timeout=120.0,
    )
    assert len(g2["output_ids"]) == 4
    assert g2["cached_tokens"] >= 24

    # The hit shows up in the node's Prometheus metrics.
    metrics = _get(serve_url + "/metrics")
    cached = [
        l for l in metrics.splitlines()
        if l.startswith("radixmesh_engine_cached_tokens_total") and not l.startswith("#")
    ]
    assert cached and any(float(l.rsplit(" ", 1)[1]) >= 24 for l in cached)
