"""Telemetry history (obs/timeseries.py): change-compressed bounded
rings over every registered family + the derived planes, cursor
pagination on a sample boundary, self-accounting, seam isolation, and
the burn-tracker feed — all on a virtual clock (no sleeps except the
one thread smoke test)."""

import threading

import pytest

from radixmesh_tpu.obs.metrics import Registry, get_registry, set_registry
from radixmesh_tpu.obs.timeseries import TelemetryHistory

pytestmark = pytest.mark.quick


@pytest.fixture(autouse=True)
def fresh_registry():
    old = set_registry(Registry())
    yield
    set_registry(old)


class FakeFleet:
    def __init__(self):
        self.scores = {0: 1.0, 1: 1.0}
        self.heat = {"7": 50.0, "9": 5.0}

    def health(self):
        return {
            r: {"score": s, "age_s": 0.1, "reasons": [], "role": "prefill",
                "lifecycle": "active"}
            for r, s in self.scores.items()
        }

    def digests(self):
        class D:
            replication_lag_s = 0.05

        return {r: D() for r in self.scores}

    def shard_heat(self):
        mean = sum(self.heat.values()) / len(self.heat)
        return {
            "shards": dict(self.heat),
            "skew_score": max(self.heat.values()) / mean,
            "reporters": 2,
        }


class FakeMesh:
    sharded = True

    def __init__(self):
        self.fleet = FakeFleet()


class FakeAcct:
    def report(self):
        return {
            "prefill": {"mfu": 0.1, "pad_fraction": 0.2, "waves": 3},
            "decode": {"mfu": 0.05, "pad_fraction": 0.0, "waves": 9},
        }


class FakeEngine:
    step_acct = FakeAcct()


class FakeSLO:
    def __init__(self):
        self.counts = {"t0": {"admitted": 0, "shed": 0}}

    def burn_counts(self):
        return {t: dict(c) for t, c in self.counts.items()}


def _hist(**kw):
    kw.setdefault("interval_s", 1.0)
    kw.setdefault("capacity", 16)
    return TelemetryHistory(**kw)


class TestRings:
    def test_change_compression_flat_series_is_one_point(self):
        g = get_registry().gauge("radixmesh_test_flag", "t")
        g.set(1.0)
        h = _hist()
        for t in range(8):
            h.sample(t=float(t))
        pts = h.query(family="radixmesh_test_flag")["series"][
            "radixmesh_test_flag"
        ]["points"]
        assert len(pts) == 1  # never changed after the first sample
        g.set(2.0)
        h.sample(t=8.0)
        pts = h.query(family="radixmesh_test_flag")["series"][
            "radixmesh_test_flag"
        ]["points"]
        assert [p[2] for p in pts] == [1.0, 2.0]

    def test_capacity_bounds_points(self):
        c = get_registry().counter("radixmesh_test_total", "t")
        h = _hist(capacity=8)
        for t in range(50):
            c.inc()
            h.sample(t=float(t))
        pts = h.query(family="radixmesh_test_total")["series"][
            "radixmesh_test_total"
        ]["points"]
        assert len(pts) == 8  # ring bound
        assert pts[-1][0] == 49  # ...holding the newest samples

    def test_vanished_series_pruned_after_a_window(self):
        h = _hist(capacity=4, mesh=FakeMesh())
        mesh = h.mesh
        h.sample(t=0.0)
        assert any(
            n.startswith("shard:heat") for n in h.query()["series"]
        )
        mesh.fleet.heat = {}  # the shard map empties
        mesh.fleet.scores = {}
        for t in range(1, 10):
            h.sample(t=float(t))
        names = set(h.query()["series"])
        assert not any(n.startswith("shard:heat") for n in names)

    def test_max_series_cap_drops_and_counts(self):
        h = _hist(max_series=3)
        h.sample(t=0.0)  # the self-accounting families already exceed 3
        assert h.stats()["series"] == 3
        assert h.stats()["dropped_series"] > 0

    def test_dropped_series_counts_series_not_sample_writes(self):
        # The counter means "series dropped", so the SAME refused names
        # must not inflate it on every subsequent tick (the refused
        # ledger only resets with the once-per-window prune sweep).
        h = _hist(max_series=3, capacity=64)
        h.sample(t=0.0)
        first = h.stats()["dropped_series"]
        for t in range(1, 20):
            h.sample(t=float(t))
        assert h.stats()["dropped_series"] == first


class TestDerivedSeams:
    def test_fleet_heat_step_slo_series(self):
        slo = FakeSLO()
        slo.counts = {"t0": {"admitted": 10, "shed": 2}}
        h = _hist(mesh=FakeMesh(), engine=FakeEngine(), slo=slo)
        h.sample(t=0.0)
        s = h.query()["series"]
        assert s['fleet:health_score{rank="0"}']["points"][0][2] == 1.0
        assert s["fleet:alive_nodes"]["points"][0][2] == 2.0
        assert s['shard:heat{shard="7"}']["points"][0][2] == 50.0
        assert s["shard:skew_ratio"]["points"][0][2] == pytest.approx(
            50.0 / 27.5
        )
        assert s['step:mfu{kind="prefill"}']["points"][0][2] == 0.1
        assert s['slo:admitted{tenant="t0"}']["points"][0][2] == 10.0
        assert s['slo:shed{tenant="t0"}']["points"][0][2] == 2.0

    def test_broken_seam_loses_its_series_not_the_sample(self):
        class BrokenMesh:
            sharded = True

            @property
            def fleet(self):
                raise RuntimeError("boom")

        c = get_registry().counter("radixmesh_test_total", "t")
        c.inc()
        h = _hist(mesh=BrokenMesh())
        seq = h.sample(t=0.0)
        assert seq == 0
        assert "radixmesh_test_total" in h.query()["series"]

    def test_burn_tracker_fed_per_sample(self):
        slo = FakeSLO()
        h = _hist(slo=slo)

        class Sink:
            def __init__(self):
                self.calls = []

            def sample(self, counts, t=None):
                self.calls.append((dict(counts), t))

        sink = Sink()
        h.bind_burn_tracker(sink)
        h.bind_burn_tracker(sink)  # idempotent
        slo.counts = {"t0": {"admitted": 5, "shed": 1}}
        h.sample(t=42.0)
        assert sink.calls == [({"t0": {"admitted": 5, "shed": 1}}, 42.0)]


class TestQueryPagination:
    def _filled(self, samples=10):
        c = get_registry().counter("radixmesh_test_total", "t")
        g = get_registry().gauge("radixmesh_test_flag", "t")
        h = _hist(capacity=64)
        for t in range(samples):
            c.inc()
            g.set(float(t % 2))
            h.sample(t=float(t))
        return h

    def test_since_cursor_returns_only_newer_points(self):
        h = self._filled()
        full = h.query(family="radixmesh_test_total")
        pts = full["series"]["radixmesh_test_total"]["points"]
        mid = pts[4][0]
        page = h.query(family="radixmesh_test_total", since=mid)
        assert all(
            p[0] > mid
            for p in page["series"]["radixmesh_test_total"]["points"]
        )

    def test_limit_cuts_on_a_sample_boundary(self):
        h = self._filled()
        page = h.query(since=-1, limit=5)
        cutoff = page["next_since"]
        # Every series' page ends at or before the cutoff seq, and no
        # sample is split across the boundary.
        for body in page["series"].values():
            assert all(p[0] <= cutoff for p in body["points"])
        assert page["has_more"] is True

    def test_pagination_loop_terminates_and_covers_everything(self):
        h = self._filled()
        all_pts = {
            name: [tuple(p) for p in body["points"]]
            for name, body in h.query(limit=1 << 62)["series"].items()
        }
        got: dict[str, list] = {name: [] for name in all_pts}
        since, pages = -1, 0
        while True:
            page = h.query(since=since, limit=7)
            for name, body in page["series"].items():
                got.setdefault(name, []).extend(
                    tuple(p) for p in body["points"]
                )
            pages += 1
            assert pages < 100
            if not page["has_more"]:
                break
            assert page["next_since"] > since
            since = page["next_since"]
        for name, pts in all_pts.items():
            assert got[name] == pts

    def test_unchanged_series_carries_last_value(self):
        h = self._filled()
        seq = h.query()["seq"]
        page = h.query(family="radixmesh_test_total", since=seq)
        body = page["series"]["radixmesh_test_total"]
        assert body["points"] == []
        assert body["last"][1] == 10.0  # current value, cursor-free


class TestSelfAccounting:
    def test_history_families_registered_and_emitted(self):
        h = _hist()
        h.sample(t=0.0)
        snap = get_registry().snapshot()
        assert snap["radixmesh_history_samples_total"] == 1.0
        assert snap["radixmesh_history_sample_seconds_count"] == 1.0
        assert snap["radixmesh_history_series"] > 0
        assert snap["radixmesh_history_points"] > 0
        assert "radixmesh_history_dropped_series_total" in snap
        assert h.stats()["sample_seconds_total"] > 0.0

    def test_sampler_cost_visible_in_its_own_rings(self):
        h = _hist()
        h.sample(t=0.0)
        h.sample(t=1.0)
        assert (
            "radixmesh_history_samples_total" in h.query()["series"]
        )


class TestThread:
    def test_start_close_samples(self):
        h = TelemetryHistory(interval_s=0.01, capacity=32)
        h.start()
        try:
            deadline = threading.Event()
            for _ in range(200):
                if h.stats()["seq"] >= 2:
                    break
                deadline.wait(0.01)
            assert h.stats()["seq"] >= 2
        finally:
            h.close()
        assert h.last_sample_age_s() < 60.0

    def test_zero_interval_refuses_start(self):
        with pytest.raises(ValueError):
            TelemetryHistory(interval_s=0.0).start()
