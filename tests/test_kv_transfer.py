"""Async KV-movement plane (``cache/kv_transfer.py``): staged restores
that never block the decode loop, fused write-back off the engine
thread, PREFETCH hint safety (idempotent / droppable / structure-
preserving), and the streamed disagg handoff."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from radixmesh_tpu.cache.host_cache import HierarchicalCache, HostKVStore
from radixmesh_tpu.cache.kv_pool import PagedKVPool
from radixmesh_tpu.cache.kv_transfer import KVTransferPlane
from radixmesh_tpu.engine.engine import Engine
from radixmesh_tpu.engine.request import RequestState, SamplingParams
from radixmesh_tpu.models.llama import ModelConfig, init_params

pytestmark = pytest.mark.quick

PAGE = 4


@pytest.fixture(scope="module")
def tiny():
    cfg = ModelConfig.tiny()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def make_engine(tiny, **kw):
    cfg, params = tiny
    kw.setdefault("num_slots", 512)
    kw.setdefault("page_size", PAGE)
    kw.setdefault("max_batch", 2)
    kw.setdefault("host_cache_slots", 1024)
    kw.setdefault("kv_transfer_async", True)
    kw.setdefault("kv_transfer_chunk_tokens", 16)
    return Engine(cfg, params, **kw)


def drive(eng, reqs, max_steps=5000):
    for _ in range(max_steps):
        if not eng.has_work():
            break
        eng.step()
    assert all(r.state is RequestState.FINISHED for r in reqs)


def close(eng):
    if eng.kv_transfer is not None:
        eng.kv_transfer.close()


PROMPT = list(range(1, 120))
SAMP = SamplingParams(max_new_tokens=4)


def seed_and_evict(eng, prompt=PROMPT):
    out = eng.generate([prompt], SAMP)
    assert eng.tree.evict(100_000) > 0
    if eng.kv_transfer is not None:
        assert eng.kv_transfer.wait_host_ready()
    return out


class TestStagedRestore:
    def test_restore_round_trip_identical_output(self, tiny):
        """evict → host tier → staged restore → identical generation
        (the engine-level equivalence the property tests below pin at
        the pool level)."""
        eng = make_engine(tiny)
        try:
            out1 = seed_and_evict(eng)
            req = eng.add_request(PROMPT, SAMP)
            drive(eng, [req])
            assert req.generated == out1[0]
            # The retry was a (restored) cache hit, not a recompute.
            assert eng.stats.cached_tokens >= 100
        finally:
            close(eng)

    def test_decode_steps_complete_while_restore_in_flight(self, tiny):
        """THE acceptance property: a host-tier admission never blocks
        the decode loop. The stage barrier holds the restore open for a
        deterministic window; the running request must keep producing
        tokens through it."""
        eng = make_engine(tiny)
        try:
            seed_and_evict(eng)
            # A running request decoding while the restore is in flight.
            bg = eng.add_request(
                list(range(200, 240)), SamplingParams(max_new_tokens=64)
            )
            eng.step()
            assert bg.state is RequestState.RUNNING
            barrier = threading.Event()
            eng.kv_transfer.stage_barrier = barrier
            req = eng.add_request(PROMPT, SAMP)
            steps_at_park = None
            for _ in range(8):
                eng.step()
                if req.state is RequestState.RESTORING and steps_at_park is None:
                    steps_at_park = eng.stats.decode_steps
            assert req.state is RequestState.RESTORING
            assert steps_at_park is not None
            # Decode progressed while the restore was held in flight.
            assert eng.stats.decode_steps > steps_at_park
            barrier.set()
            eng.kv_transfer.stage_barrier = None
            drive(eng, [req, bg])
            assert eng.kv_transfer.idle()
        finally:
            close(eng)

    def test_cancel_mid_restore_releases_pages(self, tiny):
        eng = make_engine(tiny)
        try:
            seed_and_evict(eng)
            barrier = threading.Event()
            eng.kv_transfer.stage_barrier = barrier
            req = eng.add_request(PROMPT, SAMP)
            for _ in range(3):
                eng.step()
            assert req.state is RequestState.RESTORING
            assert eng.cancel(req.rid)
            assert req.state is RequestState.FINISHED
            assert req.cancelled
            barrier.set()
            eng.kv_transfer.stage_barrier = None
            # The ticket drains to completion and releases its eviction
            # shields: nothing stays protected, nothing leaks.
            deadline = time.monotonic() + 10
            while not eng.kv_transfer.idle() and time.monotonic() < deadline:
                eng.step()
            assert eng.kv_transfer.idle()
            assert eng.tree.protected_size_ == 0
        finally:
            close(eng)

    def test_sync_fallback_below_min_restore_threshold(self, tiny):
        eng = make_engine(tiny, kv_transfer_min_restore_tokens=10_000)
        try:
            out1 = seed_and_evict(eng)
            req = eng.add_request(PROMPT, SAMP)
            states = set()
            for _ in range(5000):
                if not eng.has_work():
                    break
                eng.step()
                states.add(req.state)
            # Below the threshold the synchronous path serves the hit —
            # the request never parks.
            assert RequestState.RESTORING not in states
            assert req.generated == out1[0]
        finally:
            close(eng)


class TestPrefetchHints:
    def test_hint_restores_ahead_and_is_idempotent(self, tiny):
        eng = make_engine(tiny)
        try:
            seed_and_evict(eng)
            fp_before = eng.tree.fingerprint
            nodes_before = sum(1 for _ in eng.tree._all_nodes())
            key = np.asarray(PROMPT, np.int32)
            for _ in range(3):  # duplicate delivery must be a no-op join
                eng.kv_transfer.note_hint(key)
            deadline = time.monotonic() + 10
            while not eng.kv_transfer.idle() and time.monotonic() < deadline:
                eng.step()
            assert eng.kv_transfer.idle()
            m = eng.tree.match_prefix(key)
            assert m.length >= 116  # page-aligned full prompt, device tier
            assert m.host_length == 0
            # Structure preserved: hints never split nodes or evict —
            # same token-path set, no node-count churn beyond restores.
            assert eng.tree.fingerprint == fp_before
            assert sum(1 for _ in eng.tree._all_nodes()) == nodes_before
            assert eng.tree.protected_size_ == 0
            # A hint for an already-device-resident prefix is a no-op.
            eng.kv_transfer.note_hint(key)
            eng.step()
            assert eng.kv_transfer.idle()
        finally:
            close(eng)

    def test_hint_for_evicted_prefix_is_safe(self, tiny):
        """A stale hint whose prefix left BOTH tiers must no-op."""
        eng = make_engine(tiny)
        try:
            seed_and_evict(eng)
            # Destroy the host copies too (arena pressure stand-in).
            eng.tree._evict_host(100_000)
            key = np.asarray(PROMPT, np.int32)
            eng.kv_transfer.note_hint(key)
            eng.step()
            assert eng.kv_transfer.idle()
            assert eng.tree.protected_size_ == 0
        finally:
            close(eng)

    def test_hint_racing_real_admission_joins(self, tiny):
        """Hint then immediate admission: the admission must JOIN the
        hint's in-flight restore (no double restore, no double free),
        and the request still serves the full hit."""
        eng = make_engine(tiny)
        try:
            out1 = seed_and_evict(eng)
            barrier = threading.Event()
            eng.kv_transfer.stage_barrier = barrier
            eng.kv_transfer.note_hint(np.asarray(PROMPT, np.int32))
            eng.step()  # hint converts to a held-open restore ticket
            req = eng.add_request(PROMPT, SAMP)
            for _ in range(3):
                eng.step()
            assert req.state is RequestState.RESTORING
            assert eng.kv_transfer.hints_joined >= 1
            barrier.set()
            eng.kv_transfer.stage_barrier = None
            drive(eng, [req])
            assert req.generated == out1[0]
            assert eng.kv_transfer.idle()
            assert eng.tree.protected_size_ == 0
        finally:
            close(eng)


class TestDrainRacesPrefetch:
    def test_hint_during_drain_is_dropped_without_ticket_leak(self, tiny):
        """Drain-under-chaos edge case (PR 6): a router PREFETCH hint
        lands while the node is mid-drain (the router stops hinting once
        DRAINING gossips, but in-flight frames still arrive). The hint
        must be DROPPED — counted under the "draining" outcome — and no
        restore ticket, eviction shield, or staged chunk may leak."""
        eng = make_engine(tiny)
        try:
            seed_and_evict(eng)  # host-tier prefix a hint WOULD restore
            from radixmesh_tpu.server.http_frontend import EngineRunner

            runner = EngineRunner(eng)  # not started: we drive directly
            runner.begin_drain()
            assert eng.draining
            eng.kv_transfer.note_hint(np.asarray(PROMPT, np.int32))
            for _ in range(3):
                eng.step()  # the pump sees the hint and must discard it
            assert eng.kv_transfer.idle(), "hint opened plane work mid-drain"
            assert eng.kv_transfer.stats()["active_tickets"] == 0
            assert eng.tree.protected_size_ == 0
            # The prefix is still host-tier (nothing restored it).
            m = eng.tree.match_prefix(np.asarray(PROMPT, np.int32))
            assert m.host_length > 0
            from radixmesh_tpu.obs.metrics import get_registry

            snap = get_registry().snapshot()
            drained = [
                v for k, v in snap.items()
                if k.startswith("radixmesh_kv_transfer_prefetch_hints_total")
                and 'outcome="draining"' in k
                and f'plane="{eng.name}"' in k
            ]
            assert drained and drained[0] >= 1
        finally:
            close(eng)


class TestWritebackLane:
    def test_fused_gather_per_sweep_and_arena_ordering(self, tiny):
        """One device gather per eviction sweep; a sync restore right
        behind the async write-back reads the arena only after the
        worker's write landed (wait_host_ready barrier)."""
        eng = make_engine(tiny)
        try:
            out1 = eng.generate([PROMPT], SAMP)
            assert eng.tree.evict(100_000) > 0
            assert eng.tree.wb_sweeps == 1
            assert eng.tree.wb_gathers == 1
            # Immediately re-serve through the SYNC fallback (threshold
            # forces it) — correctness depends on the read barrier.
            eng._kv_min_restore = 10_000
            req = eng.add_request(PROMPT, SAMP)
            drive(eng, [req])
            assert req.generated == out1[0]
        finally:
            close(eng)


class TestPlaneMetricsAndState:
    def test_stats_shape_and_counters(self, tiny):
        eng = make_engine(tiny)
        try:
            seed_and_evict(eng)
            req = eng.add_request(PROMPT, SAMP)
            drive(eng, [req])
            st = eng.kv_transfer.stats()
            for key in (
                "chunk_tokens", "writebacks_queued", "restores_queued",
                "staged_chunks", "pending_restore_nodes", "active_tickets",
                "hints_queued", "hints_seen", "hints_joined",
            ):
                assert key in st
            from radixmesh_tpu.obs.metrics import get_registry

            snap = get_registry().snapshot()
            restored = [
                v for k, v in snap.items()
                if k.startswith("radixmesh_kv_transfer_restored_tokens_total")
                and f'plane="{eng.name}"' in k
            ]
            assert restored and restored[0] > 0
        finally:
            close(eng)


class TestFailedWritebackDegradation:
    def test_poisoned_host_slots_degrade_without_deadlock(self, tiny):
        """A failed write-back poisons its arena slots; the next staged
        restore attempt must DROP the host copy (no garbage restore) and
        must not deadlock on the plane lock (regression: host_slots_ok
        re-acquired the non-reentrant lock inside begin_restore)."""
        eng = make_engine(tiny)
        try:
            seed_and_evict(eng)
            # Simulate a worker-side materialization failure for every
            # written-back slot.
            host_ids = [
                int(s)
                for n in eng.tree._all_nodes()
                if n.host_value is not None
                for s in n.host_value
            ]
            with eng.kv_transfer._lock:
                eng.kv_transfer._poisoned_host.update(host_ids)
            req = eng.add_request(PROMPT, SAMP)
            drive(eng, [req])  # hangs here if the lock re-entered
            # The prefix recomputed (host copy dropped, not restored).
            assert req.state is RequestState.FINISHED
            assert eng.kv_transfer.idle()
            assert eng.tree.protected_size_ == 0
            m = eng.tree.match_prefix(np.asarray(PROMPT, np.int32))
            assert m.host_length == 0  # poisoned copies are gone
        finally:
            close(eng)
