"""Heat-driven shard rebalancing (cache/rebalance.py): override wire +
supersession edges, deterministic derived-map semantics, the decision
plane's hysteresis + movement bounds, live fold/forget/rejoin gossip,
the sub-second rebalance-under-storm chaos variant (the quick-gate CI
hook), and meshcheck cleanliness of the new plane."""

import time

import numpy as np
import pytest

from radixmesh_tpu.cache.rebalance import (
    EMPTY_OVERRIDES,
    RebalanceConfig,
    RebalancePlane,
    ShardOverrides,
    decode_overrides,
    encode_overrides,
)
from radixmesh_tpu.cache.sharding import NUM_SHARDS, build_ownership

pytestmark = pytest.mark.quick


def wait_for(pred, timeout=20.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class TestOverridesWire:
    def test_round_trip(self):
        o = ShardOverrides(5, 3, {7: (0, 1, 4), 63: (2,), 0: (5, 1)})
        back = decode_overrides(encode_overrides(o))
        assert (back.epoch, back.version) == (5, 3)
        assert back.moves == o.moves

    def test_empty_round_trips(self):
        back = decode_overrides(encode_overrides(EMPTY_OVERRIDES))
        assert (back.epoch, back.version) == (0, 0)
        assert back.moves == {}

    def test_bad_magic_and_truncation_raise(self):
        arr = encode_overrides(ShardOverrides(1, 1, {3: (0, 1)}))
        bad = arr.copy()
        bad[0] ^= 0xFF
        with pytest.raises(ValueError):
            decode_overrides(bad)
        with pytest.raises(ValueError):
            decode_overrides(arr[: max(1, len(arr) - 2)])


class TestSupersession:
    def test_epoch_rollback_refused(self):
        cur = ShardOverrides(5, 1, {})
        # A LOWER epoch never supersedes, no matter the version.
        assert not ShardOverrides(4, 99, {1: (0,)}).supersedes(cur)

    def test_replay_refused(self):
        cur = ShardOverrides(5, 3, {})
        assert not ShardOverrides(5, 3, {1: (0,)}).supersedes(cur)
        assert not ShardOverrides(5, 2, {}).supersedes(cur)

    def test_newer_wins(self):
        cur = ShardOverrides(5, 3, {})
        assert ShardOverrides(5, 4, {}).supersedes(cur)
        assert ShardOverrides(6, 1, {}).supersedes(cur)
        assert ShardOverrides(6, 1, {}).supersedes(None)

    def test_without_ranks_preserves_order_pair(self):
        o = ShardOverrides(5, 3, {1: (0, 2), 2: (3,), 4: (0, 3)})
        f = o.without_ranks({3})
        assert (f.epoch, f.version) == (5, 3)
        assert set(f.moves) == {1}
        # No dead ranks: the SAME instance comes back (no churn).
        assert o.without_ranks({9}) is o
        assert o.without_ranks(set()) is o


class TestDerivedMap:
    def _pf(self, r):
        return r < 3

    def test_determinism_across_nodes(self):
        """Two nodes deriving from identical (view, rf, overrides)
        inputs — under interleaved view + override changes — always
        land on identical maps (derivation is pure)."""
        ovr = ShardOverrides(2, 1, {5: (0, 4), 9: (1, 2, 3)})
        for alive in ([0, 1, 2, 3, 4], [0, 2, 4], [1, 3]):
            a = build_ownership(alive, 2, 7, is_prefill=self._pf,
                                overrides=ovr)
            b = build_ownership(alive, 2, 7, is_prefill=self._pf,
                                overrides=ovr)
            assert a.owners == b.owners

    def test_override_replaces_only_named_shards(self):
        base = build_ownership(range(5), 2, 1, is_prefill=self._pf)
        ovr = ShardOverrides(1, 1, {5: (4, 0)})
        eff = build_ownership(range(5), 2, 1, is_prefill=self._pf,
                              overrides=ovr)
        assert eff.owners_of(5) == (4, 0)
        for sid in range(NUM_SHARDS):
            if sid != 5:
                assert eff.owners_of(sid) == base.owners_of(sid)

    def test_dead_ranks_filtered_and_empty_falls_back(self):
        base = build_ownership([0, 1, 2], 2, 1, is_prefill=self._pf)
        ovr = ShardOverrides(1, 1, {5: (9, 1, 9, 1), 6: (7, 8)})
        eff = build_ownership([0, 1, 2], 2, 1, is_prefill=self._pf,
                              overrides=ovr)
        # Dead ranks dropped, duplicates deduped in order.
        assert eff.owners_of(5) == (1,)
        # Every named rank dead: the base walk serves.
        assert eff.owners_of(6) == base.owners_of(6)


class _StaticHeatFleet:
    """FleetView heat stand-in for plane decision tests."""

    def __init__(self, shards, by_rank=None):
        self._shards = dict(shards)
        self._by_rank = by_rank or {}

    def shard_heat(self):
        vals = self._shards
        mean = sum(vals.values()) / len(vals) if vals else 0.0
        hot = max(vals, key=vals.get) if vals else None
        return {
            "shards": dict(vals),
            "by_rank": {str(r): dict(h) for r, h in self._by_rank.items()},
            "skew_score": (vals[hot] / mean) if vals and mean > 0 else 0.0,
            "hot_shard": hot,
            "reporters": max(1, len(self._by_rank)),
        }


class _FakeView:
    def __init__(self, alive, epoch=3, master=0):
        self.alive = tuple(alive)
        self.epoch = epoch
        self._master = master

    def contains(self, rank):
        return rank in self.alive

    def master_rank(self):
        return self._master


class _FakeMesh:
    """Decision-plane harness: enough MeshCache surface for tick()."""

    def __init__(self, alive=(0, 1, 2, 3, 4, 5), rf=2, rank=0):
        self.rank = rank
        self.sharded = True
        self.view = _FakeView(alive)
        self.overrides = EMPTY_OVERRIDES
        self.fleet = _StaticHeatFleet({})
        self.adopted = []

        class _Cfg:
            @staticmethod
            def is_prefill_rank(r):
                return r < 4

        self.cfg = _Cfg()
        self._base = build_ownership(
            alive, rf, self.view.epoch,
            is_prefill=self.cfg.is_prefill_rank,
        )
        self.ownership = self._base
        self._node_label = f"fake@{rank}"

    def base_owners_of(self, sid):
        return self._base.owners_of(sid)

    def adopt_overrides(self, ovr):
        if not ovr.supersedes(self.overrides):
            return False
        self.overrides = ovr
        self.ownership = build_ownership(
            self.view.alive, 2, self.view.epoch,
            is_prefill=self.cfg.is_prefill_rank, overrides=ovr,
        )
        self.adopted.append(ovr)
        return True


class TestPlaneDecisions:
    def _plane(self, mesh, **kw):
        cfg = RebalanceConfig(
            interval_s=3600.0, skew_trigger=3.0, boost_factor=2.0,
            shrink_factor=1.2, rf_boost=2, max_moves_per_round=2, **kw,
        )
        return RebalancePlane(mesh, cfg)

    def test_non_decider_never_acts(self):
        mesh = _FakeMesh(rank=1)  # master is 0
        plane = self._plane(mesh)
        mesh.fleet = _StaticHeatFleet({7: 100.0, 1: 1.0, 2: 1.0})
        rep = plane.tick()
        assert rep["decider"] is False and not mesh.adopted
        plane.close()

    def test_balanced_fleet_never_moves(self):
        mesh = _FakeMesh()
        plane = self._plane(mesh)
        mesh.fleet = _StaticHeatFleet({1: 5.0, 2: 5.2, 3: 4.8})
        rep = plane.tick()
        assert rep["adopted"] is False and not mesh.adopted
        plane.close()

    def test_boost_grows_owner_superset_bounded(self):
        mesh = _FakeMesh()
        plane = self._plane(mesh)
        # Three hot shards but a movement bound of 2: hottest first.
        mesh.fleet = _StaticHeatFleet(
            {
                7: 100.0, 9: 90.0, 11: 80.0,
                1: 1.0, 2: 1.0, 3: 1.0, 4: 1.0, 5: 1.0, 6: 1.0,
            },
        )
        rep = plane.tick()
        assert rep["adopted"] is True
        assert rep["boosted"] == [7, 9]  # bounded, hottest first
        for sid in rep["boosted"]:
            base = set(mesh.base_owners_of(sid))
            new = set(mesh.ownership.owners_of(sid))
            assert base <= new and len(new) > len(base)
        # Untouched shard keeps its base walk.
        assert mesh.ownership.owners_of(11) == mesh.base_owners_of(11)
        assert plane.moves_in_window(60.0) == 2
        plane.close()

    def test_shrink_hysteresis_band(self):
        mesh = _FakeMesh()
        plane = self._plane(mesh)
        mesh.fleet = _StaticHeatFleet({7: 100.0, 1: 1.0, 2: 1.0, 3: 1.0})
        assert plane.tick()["boosted"] == [7]
        # Inside the band (above shrink_factor x mean): boost STICKS —
        # no flapping on a hovering load.
        mesh.fleet = _StaticHeatFleet({7: 40.0, 1: 20.0, 2: 20.0, 3: 20.0})
        rep = plane.tick()
        assert rep["shrunk"] == [] and 7 in mesh.overrides.moves
        # Below the band's floor: shrink back to the base walk.
        mesh.fleet = _StaticHeatFleet({7: 1.0, 1: 20.0, 2: 20.0, 3: 20.0})
        rep = plane.tick()
        assert rep["shrunk"] == [7]
        assert 7 not in mesh.overrides.moves
        assert mesh.ownership.owners_of(7) == mesh.base_owners_of(7)
        plane.close()

    def test_boost_appends_per_role(self):
        mesh = _FakeMesh()
        plane = self._plane(mesh)
        mesh.fleet = _StaticHeatFleet({7: 100.0, 1: 1.0, 2: 1.0, 3: 1.0})
        plane.tick()
        new = mesh.ownership.owners_of(7)
        pf = [r for r in new if mesh.cfg.is_prefill_rank(r)]
        dc = [r for r in new if not mesh.cfg.is_prefill_rank(r)]
        base = mesh.base_owners_of(7)
        base_pf = [r for r in base if mesh.cfg.is_prefill_rank(r)]
        base_dc = [r for r in base if not mesh.cfg.is_prefill_rank(r)]
        assert len(pf) > len(base_pf)  # prefill extras appended
        assert len(dc) >= len(base_dc)  # decode never loses seats
        plane.close()

    def test_propose_explicit_move(self):
        mesh = _FakeMesh()
        plane = self._plane(mesh)
        assert plane.propose(9, (4, 0), cause="move")
        assert mesh.ownership.owners_of(9) == (4, 0)
        assert plane.moves_in_window(60.0) == 1
        plane.close()

    def test_explicit_move_is_not_elastically_shrunk(self):
        """Review hardening: the shrink policy only touches BOOST-shaped
        entries (strict supersets of the base walk) — an operator's
        explicit owner-set replacement of a cold shard must not be
        quietly reverted by the next tick."""
        mesh = _FakeMesh()
        plane = self._plane(mesh)
        assert plane.propose(9, (4, 0), cause="move")
        # Shard 9 is stone cold relative to the fleet: a boost-shaped
        # entry would shrink here.
        mesh.fleet = _StaticHeatFleet({1: 20.0, 2: 20.0, 3: 20.0})
        rep = plane.tick()
        assert rep["shrunk"] == []
        assert mesh.overrides.moves.get(9) == (4, 0)
        plane.close()

    def test_stats_shape(self):
        mesh = _FakeMesh()
        plane = self._plane(mesh)
        st = plane.stats()
        assert st["decider"] is True and st["rounds"] == 0
        plane.close()
        assert getattr(mesh, "rebalance", None) is None


@pytest.fixture
def small_cluster():
    from radixmesh_tpu.cache.mesh_cache import MeshCache
    from radixmesh_tpu.comm.inproc import InprocHub
    from radixmesh_tpu.config import MeshConfig, NodeRole

    InprocHub.reset_default()
    prefill, decode, routers = (
        ["tp0", "tp1", "tp2", "tp3"], ["td0", "td1"], ["tr0", "tr1"],
    )
    nodes = []
    for addr in prefill + decode + routers:
        cfg = MeshConfig(
            prefill_nodes=prefill,
            decode_nodes=decode,
            router_nodes=routers,
            local_addr=addr,
            protocol="inproc",
            tick_interval_s=0.05,
            gc_interval_s=60.0,
            failure_timeout_s=60.0,
            replication_factor=2,
            heat_half_life_s=0.15,
        )
        nodes.append(MeshCache(cfg, pool=None).start())
    for n in nodes:
        assert n.wait_ready(timeout=20)
    ring = [n for n in nodes if n.role is not NodeRole.ROUTER]
    router_meshes = [n for n in nodes if n.role is NodeRole.ROUTER]
    yield nodes, ring, router_meshes
    for n in nodes:
        n.close()
    InprocHub.reset_default()


class TestLiveFold:
    def test_adopt_gossips_and_converges(self, small_cluster):
        nodes, ring, routers = small_cluster
        master = ring[0]
        sid = 11
        base = master.base_owners_of(sid)
        extra = next(n.rank for n in ring if n.rank not in base)
        target = base + (extra,)
        ovr = ShardOverrides(master.view.epoch, 1, {sid: target})
        assert master.adopt_overrides(ovr)
        assert wait_for(
            lambda: all(
                (n.overrides.epoch, n.overrides.version)
                == (ovr.epoch, ovr.version)
                for n in nodes
            )
        ), "override gossip never converged"
        for n in nodes:
            assert n.ownership.owners_of(sid) == target

    def test_fold_refuses_rollback_and_replay(self, small_cluster):
        nodes, ring, _ = small_cluster
        master = ring[0]
        epoch = master.view.epoch
        assert master.adopt_overrides(
            ShardOverrides(epoch, 2, {3: (0, 1)})
        )
        # Replay (same pair) and version rollback refused.
        assert not master.adopt_overrides(
            ShardOverrides(epoch, 2, {3: (2,)})
        )
        assert not master.adopt_overrides(
            ShardOverrides(epoch, 1, {3: (2,)})
        )
        # Epoch rollback refused even with a huge version.
        assert not master.adopt_overrides(
            ShardOverrides(epoch - 1, 99, {3: (2,)})
        )
        assert master.overrides.moves[3] == (0, 1)

    def test_override_forgotten_when_rank_leaves(self, small_cluster):
        nodes, ring, routers = small_cluster
        master = ring[0]
        leaver = ring[-1]  # td1: a decode node we can drop
        sid = 21
        target = tuple(master.base_owners_of(sid)) + (leaver.rank,)
        assert master.adopt_overrides(
            ShardOverrides(master.view.epoch, 1, {sid: target})
        )
        assert wait_for(
            lambda: all(sid in n.overrides.moves for n in nodes)
        )
        # The overridden rank LEAVES (graceful departure): every node
        # forgets the entry (FleetView.forget discipline) and derives
        # the base walk over the survivors.
        leaver.broadcast_leave()
        assert wait_for(
            lambda: all(
                sid not in n.overrides.moves
                for n in nodes
                if n is not leaver
            )
        ), "override naming the leaver survived its departure"
        for n in ring[:-1]:
            assert leaver.rank not in n.ownership.owners_of(sid)

    def test_rejoiner_learns_overrides_on_join(self, small_cluster):
        from radixmesh_tpu.cache.oplog import Oplog, OplogType

        nodes, ring, routers = small_cluster
        master = ring[0]
        sid = 33
        target = tuple(master.base_owners_of(sid)) + tuple(
            r for r in (ring[1].rank,) if r not in master.base_owners_of(sid)
        )
        assert master.adopt_overrides(
            ShardOverrides(master.view.epoch, 1, {sid: target})
        )
        joiner = ring[2]
        # Simulate a cold (re)boot: the joiner's override state resets
        # and it re-announces itself; the master's JOIN answer must
        # re-gossip the current overrides or the joiner's owner sets
        # fork from the fleet's.
        with joiner._lock:
            joiner.overrides = EMPTY_OVERRIDES
        with joiner._lock:
            joiner._broadcast(
                Oplog(
                    op_type=OplogType.JOIN,
                    origin_rank=joiner.rank,
                    logic_id=joiner._logic_op.next(),
                    ttl=joiner._data_ttl(),
                )
            )
        assert wait_for(
            lambda: joiner.overrides.moves.get(sid) == tuple(target)
        ), "the JOIN answer never re-announced the override map"


class TestRebalanceStormQuick:
    def test_sub_second_storm_skew_drops_zero_failed(self, small_cluster):
        """The quick-gate CI variant of the chaos rebalance phase
        (satellite: the acceptance scenario at sub-second scale): a
        zipf storm's skew strictly drops once the decider boosts the
        hot shards, with zero failed requests mid-move and the
        override version converged fleet-wide."""
        from radixmesh_tpu.workload import _chaos_rebalance_phase

        nodes, ring, routers = small_cluster
        by_addr = {n.cfg.local_addr: n for n in ring}
        rng = np.random.default_rng(0)
        rep = _chaos_rebalance_phase(
            ring=ring,
            router_mesh=routers[0],
            by_addr=by_addr,
            rng=rng,
            wait_for=wait_for,
            key_len=12,
            zipf_keys=12,
            zipf_inserts=90,
            wave_s=0.3,
            settle_s=0.4,
            mid_requests=12,
            timeout_s=15.0,
        )
        assert rep["performed"]
        assert rep["skew_dropped"] and rep["skew_after"] < rep["skew_before"]
        assert rep["failed_mid_move"] == 0
        assert rep["moves"] >= 1 and rep["moves_bounded"]
        assert rep["overrides_converged"]
        assert rep["handoff_entries"] >= 1
        # Sub-second phase (the quick-gate budget): the two waves plus
        # the settle window.
        assert rep["rebalance_s"] < 3.0


class TestMeshcheckOnPlane:
    def test_rebalance_plane_is_statically_clean(self):
        """The acceptance gate's static half: meshcheck reports ZERO
        findings on the new plane's files, and the seeded
        second-writer-of-overrides control still trips (so the clean
        verdict is evidence, not a broken checker)."""
        from radixmesh_tpu.analysis import check_tree
        from radixmesh_tpu.analysis.controls import run_positive_controls

        res = check_tree()
        plane = [
            f for f in res.findings
            if f.file in ("cache/rebalance.py", "router/front_door.py")
        ]
        assert not plane, "\n".join(str(f) for f in plane)
        controls = run_positive_controls()
        ovr = [
            c for c in controls
            if c.invariant == "single-writer-overrides"
        ]
        assert ovr and all(c.tripped for c in ovr), (
            "the seeded second-writer-of-overrides control no longer "
            "trips — the single-writer contract on the rebalance plane "
            "is aspirational"
        )
