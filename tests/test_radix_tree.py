"""Unit tests for the host-side radix tree.

The reference has no unit tests (SURVEY §4); these cover the capability set
of ``radix_cache.py:87-436``: match/insert/split, paged keys, LRU eviction,
lock refs, size accounting, and the event journal.
"""

import numpy as np
import pytest

from radixmesh_tpu.cache.radix_tree import (
    AllBlocksCleared,
    BlockRemoved,
    BlockStored,
    MatchResult,
    RadixTree,
    match_len,
)

pytestmark = pytest.mark.quick


def ids(n, start=0):
    return np.arange(start, start + n, dtype=np.int32)


def make_tree(**kw):
    t = [0.0]

    def clock():
        t[0] += 1.0
        return t[0]

    return RadixTree(time_fn=clock, **kw)


class TestMatchLen:
    def test_basic(self):
        assert match_len(ids(5), ids(5)) == 5
        assert match_len(ids(5), ids(3)) == 3
        assert match_len(np.array([1, 2, 9]), np.array([1, 2, 3])) == 2
        assert match_len(np.array([7]), np.array([1])) == 0
        assert match_len(ids(0), ids(5)) == 0


class TestInsertMatch:
    def test_empty_tree_match(self):
        tree = make_tree()
        res = tree.match_prefix([1, 2, 3])
        assert res.length == 0
        assert res.last_node is tree.root

    def test_insert_then_match_exact(self):
        tree = make_tree()
        key, val = [1, 2, 3], np.array([10, 11, 12], dtype=np.int32)
        assert tree.insert(key, val) == 0
        res = tree.match_prefix(key)
        assert res.length == 3
        np.testing.assert_array_equal(res.indices(), val)

    def test_match_partial_splits_node(self):
        tree = make_tree()
        tree.insert([1, 2, 3, 4], np.array([10, 11, 12, 13], dtype=np.int32))
        res = tree.match_prefix([1, 2, 99])
        assert res.length == 2
        np.testing.assert_array_equal(res.indices(), [10, 11])
        # The node was split: the matched node holds exactly [1, 2].
        np.testing.assert_array_equal(res.last_node.key, [1, 2])
        # Full key still reachable.
        res2 = tree.match_prefix([1, 2, 3, 4])
        assert res2.length == 4
        np.testing.assert_array_equal(res2.indices(), [10, 11, 12, 13])

    def test_readonly_match_does_not_split(self):
        tree = make_tree()
        tree.insert([1, 2, 3, 4], np.array([10, 11, 12, 13], dtype=np.int32))
        before = tree.total_size()
        res = tree.match_prefix([1, 2], split_partial=False)
        assert res.length == 2
        np.testing.assert_array_equal(res.indices(), [10, 11])
        assert tree.total_size() == before
        # Node count unchanged: root has a single 4-token child.
        assert len(tree.root.children) == 1
        only = next(iter(tree.root.children.values()))
        assert len(only.key) == 4
        # last_node anchors at the deepest FULLY matched node, so locking it
        # never protects tokens beyond the matched prefix.
        assert res.last_node is tree.root
        tree.inc_lock_ref(res.last_node)
        assert tree.protected_size() == 0
        assert tree.evict(100) == 4

    def test_insert_returns_existing_prefix_len(self):
        tree = make_tree()
        tree.insert([1, 2, 3], np.array([10, 11, 12], dtype=np.int32))
        got = tree.insert([1, 2, 3, 4, 5], np.array([10, 11, 12, 13, 14], dtype=np.int32))
        assert got == 3
        res = tree.match_prefix([1, 2, 3, 4, 5])
        assert res.length == 5

    def test_insert_idempotent(self):
        tree = make_tree()
        v = np.array([10, 11, 12], dtype=np.int32)
        tree.insert([1, 2, 3], v)
        assert tree.insert([1, 2, 3], v) == 3
        assert tree.total_size() == 3

    def test_branching(self):
        tree = make_tree()
        tree.insert([1, 2, 3], np.array([10, 11, 12], dtype=np.int32))
        tree.insert([1, 2, 7, 8], np.array([10, 11, 20, 21], dtype=np.int32))
        tree.insert([5, 6], np.array([30, 31], dtype=np.int32))
        assert tree.match_prefix([1, 2, 3]).length == 3
        np.testing.assert_array_equal(
            tree.match_prefix([1, 2, 7, 8]).indices(), [10, 11, 20, 21]
        )
        np.testing.assert_array_equal(tree.match_prefix([5, 6, 9]).indices(), [30, 31])
        assert tree.total_size() == 2 + 1 + 2 + 2  # [1,2],[3],[7,8],[5,6]

    def test_value_length_mismatch_raises(self):
        tree = make_tree()
        with pytest.raises(ValueError):
            tree.insert([1, 2, 3], np.array([1], dtype=np.int32))


class TestPaged:
    def test_paged_match_whole_pages_only(self):
        tree = make_tree(page_size=4)
        tree.insert(ids(8), ids(8, start=100))
        # 6-token query matches only the first full page (4 tokens).
        res = tree.match_prefix(ids(6))
        assert res.length == 4
        np.testing.assert_array_equal(res.indices(), ids(4, start=100))

    def test_paged_insert_truncates_partial_page(self):
        tree = make_tree(page_size=4)
        tree.insert(ids(6), ids(6, start=100))
        assert tree.total_size() == 4

    def test_paged_divergence_inside_page(self):
        tree = make_tree(page_size=2)
        tree.insert([1, 2, 3, 4], np.array([10, 11, 12, 13], dtype=np.int32))
        # Diverges at token 3 (inside second page) -> only first page matches.
        res = tree.match_prefix([1, 2, 3, 9])
        assert res.length == 2


class TestEviction:
    def test_evict_lru_and_free_callback(self):
        freed = []
        tree = make_tree(on_free=lambda idx: freed.append(np.array(idx)))
        tree.insert([1, 2], np.array([10, 11], dtype=np.int32))
        tree.insert([3, 4], np.array([20, 21], dtype=np.int32))
        tree.insert([5, 6], np.array([30, 31], dtype=np.int32))
        tree.match_prefix([1, 2])  # refresh [1,2] -> LRU is [3,4]
        n = tree.evict(2)
        assert n == 2
        assert tree.match_prefix([3, 4]).length == 0
        assert tree.match_prefix([1, 2]).length == 2
        np.testing.assert_array_equal(np.concatenate(freed), [20, 21])

    def test_evict_respects_lock(self):
        tree = make_tree()
        tree.insert([1, 2], np.array([10, 11], dtype=np.int32))
        res = tree.match_prefix([1, 2])
        tree.inc_lock_ref(res.last_node)
        assert tree.evict(10) == 0
        assert tree.match_prefix([1, 2]).length == 2
        tree.dec_lock_ref(res.last_node)
        assert tree.evict(10) == 2
        assert tree.match_prefix([1, 2]).length == 0

    def test_evict_cascades_to_parent(self):
        tree = make_tree()
        tree.insert([1, 2, 3, 4], np.array([10, 11, 12, 13], dtype=np.int32))
        tree.match_prefix([1, 2])  # split into [1,2] -> [3,4]
        assert tree.evict(4) == 4
        assert tree.total_size() == 0

    def test_size_accounting(self):
        tree = make_tree()
        tree.insert([1, 2, 3], np.array([10, 11, 12], dtype=np.int32))
        assert tree.evictable_size() == 3
        assert tree.protected_size() == 0
        res = tree.match_prefix([1, 2, 3])
        tree.inc_lock_ref(res.last_node)
        assert tree.evictable_size() == 0
        assert tree.protected_size() == 3
        tree.dec_lock_ref(res.last_node)
        assert tree.evictable_size() == 3
        assert tree.protected_size() == 0

    def test_lock_accounting_across_split(self):
        tree = make_tree()
        tree.insert([1, 2, 3, 4], np.array([10, 11, 12, 13], dtype=np.int32))
        res = tree.match_prefix([1, 2])  # splits; lock only the [1,2] node
        tree.inc_lock_ref(res.last_node)
        assert tree.protected_size() == 2
        assert tree.evictable_size() == 2
        # Only the unlocked tail can be evicted.
        assert tree.evict(100) == 2
        tree.dec_lock_ref(res.last_node)
        assert tree.evict(100) == 2


class TestEventsAndReset:
    def test_store_and_remove_events(self):
        tree = make_tree(enable_events=True)
        ev0 = tree.take_events()
        assert any(isinstance(e, AllBlocksCleared) for e in ev0)
        tree.insert([1, 2, 3], np.array([10, 11, 12], dtype=np.int32))
        (stored,) = [e for e in tree.take_events() if isinstance(e, BlockStored)]
        assert stored.token_ids == (1, 2, 3)
        assert stored.parent_block_hash is None
        tree.evict(3)
        (removed,) = [e for e in tree.take_events() if isinstance(e, BlockRemoved)]
        # Every per-page block hash is reported, not just the last one, so an
        # external observer mirroring the journal stays consistent.
        assert removed.block_hashes == stored.block_hashes

    def test_event_parent_chaining(self):
        tree = make_tree(enable_events=True)
        tree.insert([1, 2], np.array([10, 11], dtype=np.int32))
        tree.insert([1, 2, 3, 4], np.array([10, 11, 12, 13], dtype=np.int32))
        events = [e for e in tree.take_events() if isinstance(e, BlockStored)]
        assert len(events) == 2
        assert events[1].parent_block_hash == events[0].block_hashes[-1]

    def test_event_chaining_survives_split(self):
        tree = make_tree(enable_events=True)
        tree.insert([1, 2, 3, 4], np.array([10, 11, 12, 13], dtype=np.int32))
        (e0,) = [e for e in tree.take_events() if isinstance(e, BlockStored)]
        tree.insert([1, 2, 9, 9], np.array([10, 11, 30, 31], dtype=np.int32))
        (e1,) = [e for e in tree.take_events() if isinstance(e, BlockStored)]
        # The new [9,9] leaf chains off the hash of the stored [1,2] prefix.
        assert e1.parent_block_hash == e0.block_hashes[1]
        # Evicting everything removes every hash that was ever stored.
        tree.evict(100)
        removed = [
            h
            for e in tree.take_events()
            if isinstance(e, BlockRemoved)
            for h in e.block_hashes
        ]
        assert sorted(removed) == sorted(e0.block_hashes + e1.block_hashes)

    def test_reset(self):
        tree = make_tree()
        tree.insert([1, 2, 3], np.array([10, 11, 12], dtype=np.int32))
        tree.reset()
        assert tree.total_size() == 0
        assert tree.match_prefix([1, 2, 3]).length == 0
        assert tree.evictable_size() == 0

    def test_reset_returns_slots_to_pool(self):
        from radixmesh_tpu.cache.kv_pool import PagedKVPool
        import jax.numpy as jnp

        pool = PagedKVPool(
            num_slots=8, num_layers=1, num_kv_heads=1, head_dim=2, dtype=jnp.float32
        )
        tree = make_tree(on_free=pool.free)
        tree.insert(np.arange(8), pool.alloc(8))
        assert pool.free_slots == 0
        tree.reset()
        assert pool.free_slots == 8

    def test_all_values_flatten(self):
        tree = make_tree()
        tree.insert([1, 2], np.array([10, 11], dtype=np.int32))
        tree.insert([5], np.array([30], dtype=np.int32))
        assert sorted(tree.all_values_flatten().tolist()) == [10, 11, 30]


class TestFingerprint:
    """Order-independent tree fingerprint (the fleet convergence audit's
    foundation, ``obs/fleet_plane.py``): equal key SETS must fingerprint
    equal regardless of insert order or node-split structure; any
    divergent leaf must flip it."""

    def _random_ops(self, rng, n):
        chains = [
            rng.integers(0, 6, size=rng.integers(3, 10)).astype(np.int32)
            for _ in range(3)
        ]
        ops = []
        for _ in range(n):
            chain = chains[rng.integers(0, len(chains))]
            key = chain[: rng.integers(1, len(chain) + 1)].copy()
            if rng.random() < 0.4:
                key = np.concatenate(
                    [key, rng.integers(6, 12, size=rng.integers(1, 4)).astype(np.int32)]
                )
            ops.append(key)
        return ops

    def test_any_permutation_same_fingerprint(self):
        """Property: every permutation of the same insert sequence on two
        trees yields equal fingerprints (XOR commutes; chains are pure
        path functions)."""
        rng = np.random.default_rng(7)
        for trial in range(8):
            ops = self._random_ops(rng, 20)
            ref = make_tree()
            for key in ops:
                ref.insert(key, np.arange(len(key), dtype=np.int32))
            for _ in range(3):
                perm = [ops[i] for i in rng.permutation(len(ops))]
                t = make_tree()
                for key in perm:
                    t.insert(key, np.arange(len(key), dtype=np.int32))
                assert t.fingerprint == ref.fingerprint, f"trial {trial}"
            assert ref.fingerprint != 0

    def test_single_divergent_leaf_differs(self):
        rng = np.random.default_rng(11)
        ops = self._random_ops(rng, 15)
        a, b = make_tree(), make_tree()
        for key in ops:
            a.insert(key, np.arange(len(key), dtype=np.int32))
            b.insert(key, np.arange(len(key), dtype=np.int32))
        assert a.fingerprint == b.fingerprint
        b.insert(np.array([99, 98, 97], dtype=np.int32), ids(3))
        assert a.fingerprint != b.fingerprint

    def test_match_split_does_not_change_fingerprint(self):
        """match_prefix's in-place node splits change structure but not
        the key set — the fingerprint must be structure-blind."""
        t = make_tree()
        t.insert(ids(10), ids(10))
        before = t.fingerprint
        t.match_prefix(ids(4))  # splits the 10-node at 4
        assert t.fingerprint == before
        t.insert(ids(7), ids(7))  # fully-contained prefix: no new tokens
        assert t.fingerprint == before

    def test_evict_and_delete_remove_contribution(self):
        t = make_tree()
        t.insert(ids(8), ids(8))
        empty_after_insert = t.fingerprint
        t.insert(ids(8, start=100), ids(8))
        t.evict(8)  # LRU: the first insert goes
        assert t.fingerprint != empty_after_insert
        t.evict(8)  # the second goes too
        assert t.fingerprint == 0
        # Re-inserting the same keys restores the exact fingerprint.
        t.insert(ids(8), ids(8))
        assert t.fingerprint == empty_after_insert

    def test_reset_zeroes(self):
        t = make_tree()
        t.insert(ids(6), ids(6))
        assert t.fingerprint != 0
        t.reset()
        assert t.fingerprint == 0

    def test_paged_tree_fingerprints_compare(self):
        a, b = make_tree(page_size=4), make_tree(page_size=4)
        a.insert(ids(8), ids(8))
        b.insert(ids(8), ids(8))
        assert a.fingerprint == b.fingerprint
        b.insert(ids(8, start=50), ids(8))
        assert a.fingerprint != b.fingerprint

    def test_older_than_evicts_only_stale(self):
        """TTL-sweep mode: only nodes last touched before the cutoff go."""
        t = make_tree()  # injected clock ticks 1.0 per call
        t.insert(ids(4), ids(4))
        t.insert(ids(4, start=100), ids(4))
        # Touch the second key so it is fresher than the cutoff.
        t.match_prefix(ids(4, start=100))
        cutoff = t.root.children[100].last_access_time
        freed = t.evict(10**9, older_than=cutoff)
        assert freed == 4
        assert t.match_prefix(ids(4)).length == 0
        assert t.match_prefix(ids(4, start=100)).length == 4


class TestFingerprintBuckets:
    """Bucketed fingerprint vector (anti-entropy repair,
    ``cache/repair_plane.py``): the same order-independence and
    split-invariance properties as the scalar, per bucket — plus the
    repair plane's two derived contracts: the scalar is always the XOR
    of the buckets, and a divergent key is always reachable through the
    buckets it diverges."""

    def _random_ops(self, rng, n):
        chains = [
            rng.integers(0, 6, size=rng.integers(3, 10)).astype(np.int32)
            for _ in range(3)
        ]
        ops = []
        for _ in range(n):
            chain = chains[rng.integers(0, len(chains))]
            key = chain[: rng.integers(1, len(chain) + 1)].copy()
            if rng.random() < 0.4:
                key = np.concatenate(
                    [key, rng.integers(6, 12, size=rng.integers(1, 4)).astype(np.int32)]
                )
            ops.append(key)
        return ops

    def _xor_of(self, vec):
        out = 0
        for w in vec:
            out ^= int(w)
        return out

    def test_permutation_equality(self):
        rng = np.random.default_rng(31)
        for trial in range(6):
            ops = self._random_ops(rng, 20)
            ref = make_tree()
            for key in ops:
                ref.insert(key, np.arange(len(key), dtype=np.int32))
            for _ in range(3):
                t = make_tree()
                for i in rng.permutation(len(ops)):
                    t.insert(ops[i], np.arange(len(ops[i]), dtype=np.int32))
                assert (
                    t.fingerprint_buckets() == ref.fingerprint_buckets()
                ).all(), f"trial {trial}"
            assert self._xor_of(ref.fingerprint_buckets()) == ref.fingerprint

    def test_split_invariance(self):
        """Node splits repartition a chain array between two nodes; no
        bucket may move (the repair protocol compares vectors across
        replicas whose split structures differ)."""
        t = make_tree()
        t.insert(ids(12), ids(12))
        before = t.fingerprint_buckets()
        t.match_prefix(ids(5))  # splits the 12-node at 5
        assert (t.fingerprint_buckets() == before).all()
        # A replica that INSERTED the two spans separately (different
        # structure, same key set) must agree bucket-for-bucket.
        u = make_tree()
        u.insert(ids(5), ids(5))
        u.insert(ids(12), ids(12))
        assert (u.fingerprint_buckets() == before).all()

    def test_bucket_stability_under_eviction(self):
        """Evicting a key restores the exact pre-insert vector; an empty
        tree's vector is all-zero (XOR self-inverse, per bucket)."""
        t = make_tree()
        t.insert(ids(8), ids(8))
        only_first = t.fingerprint_buckets()
        t.insert(ids(6, start=200), ids(6))
        assert (t.fingerprint_buckets() != only_first).any()
        t.match_prefix(ids(6, start=200))  # freshen the second key
        t.evict(8, older_than=t.root.children[200].last_access_time)
        assert t.match_prefix(ids(8)).length == 0  # first key evicted
        # What remains must vector-match a fresh tree holding only the
        # surviving key (eviction removed EXACTLY the evictee's words).
        u = make_tree()
        u.insert(ids(6, start=200), ids(6))
        assert (t.fingerprint_buckets() == u.fingerprint_buckets()).all()
        # Evict everything: vector must return to zero.
        t.evict(10**9)
        assert (t.fingerprint_buckets() == 0).all()
        assert t.fingerprint == 0
        # Reinsert: bit-identical vector again.
        t.insert(ids(8), ids(8))
        assert (t.fingerprint_buckets() == only_first).all()

    def test_divergent_key_lands_in_diverged_buckets(self):
        """The repair-plane invariant: whatever key two trees disagree
        on, enumerating the DIVERGED buckets on the richer tree finds a
        node whose path covers that key."""
        rng = np.random.default_rng(5)
        for trial in range(5):
            ops = self._random_ops(rng, 15)
            a, b = make_tree(), make_tree()
            for key in ops:
                a.insert(key, np.arange(len(key), dtype=np.int32))
                b.insert(key, np.arange(len(key), dtype=np.int32))
            extra = rng.integers(50, 90, size=4).astype(np.int32)
            a.insert(extra, np.arange(4, dtype=np.int32))
            diff = [
                int(i)
                for i in np.nonzero(
                    a.fingerprint_buckets() != b.fingerprint_buckets()
                )[0]
            ]
            assert diff, f"trial {trial}: divergence invisible in buckets"
            touched = a.nodes_touching_buckets(diff)
            assert any(
                len(n.key) and n.key[-1] == extra[-1] for n in touched
            ), f"trial {trial}: divergent leaf not enumerated"

    def test_path_hash_stable_across_split_structure(self):
        """Key-summary identity must match across replicas regardless of
        node boundaries: the same full path hashes equal whether stored
        as one node or split."""
        a, b = make_tree(), make_tree()
        a.insert(ids(10), ids(10))
        b.insert(ids(10), ids(10))
        b.match_prefix(ids(4))  # split b's node
        ha = {a.path_hash(n) for n in a._all_nodes() if n is not a.root}
        hb = {b.path_hash(n) for n in b._all_nodes() if n is not b.root}
        # b's extra interior node adds a PREFIX hash; the full-leaf hash
        # must be present and equal in both.
        assert ha <= hb
        assert a.path_hash(max(
            (n for n in a._all_nodes() if n is not a.root),
            key=lambda n: len(n.chain),
        )) in hb
