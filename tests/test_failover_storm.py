"""Randomized crash/rejoin storms under concurrent write load.

The scenario tests in ``test_failover.py`` each exercise ONE membership
transition in isolation; real rings see writes racing detection,
re-formation racing rejoin, and repeated epoch bumps. These seeded storms
interleave inserts, deletes, hard crashes, and rejoins, then assert the
properties that must survive ANY such history:

- every alive node converges to the same membership view;
- a fresh insert after stabilization replicates to every alive ring node
  and the router attributes it (the ring is functionally intact — missed
  inserts during an outage are acceptable cache misses by design,
  reference ``README.md:60-67`` eventual-consistency stance);
- surviving writers' allocators stay consistent through a forced GC round
  (no double free from dup entries recorded across view changes).
"""

import time

import numpy as np
import pytest

from radixmesh_tpu.cache.mesh_cache import MeshCache
from radixmesh_tpu.comm.inproc import InprocHub
from radixmesh_tpu.config import NodeRole
from tests.test_failover import (  # noqa: F401
    DECODE,
    PREFILL,
    ROUTER,
    make_node,
    wait_for,
)


@pytest.fixture(autouse=True)
def fresh_hub():
    InprocHub.reset_default()
    yield
    InprocHub.reset_default()


RING_ADDRS = PREFILL + DECODE


class StormCluster:
    def __init__(self):
        self.nodes: dict[str, MeshCache] = {
            a: make_node(a).start() for a in RING_ADDRS + ROUTER
        }
        for n in self.nodes.values():
            assert n.wait_ready(timeout=10), f"node {n.rank} never ready"
        self.dead: set[str] = set()

    def alive_ring(self) -> list[MeshCache]:
        return [
            self.nodes[a] for a in RING_ADDRS if a not in self.dead
        ]

    @property
    def router(self) -> MeshCache:
        return self.nodes[ROUTER[0]]

    def crash(self, addr: str) -> None:
        self.nodes[addr].close()  # hard crash: no leave announcement
        self.dead.add(addr)

    def rejoin(self, addr: str) -> None:
        self.nodes[addr] = make_node(addr).start()
        self.dead.discard(addr)

    def close(self) -> None:
        for n in self.nodes.values():
            n.close()


def alive_ranks(c: StormCluster) -> set[int]:
    return {n.rank for n in c.alive_ring()}


@pytest.mark.parametrize("seed", [0, 1, 4])
def test_storm_membership_and_replication_survive(seed):
    # Seed 0 is the regression schedule that found the permanent
    # membership split fixed by tick-view gossip + the silence-triggered
    # JOIN housekeeper (mesh_cache.py).
    rng = np.random.default_rng(seed)
    c = StormCluster()
    try:
        inserted = 0
        for _ in range(14):
            ring = c.alive_ring()
            roll = rng.random()
            if roll < 0.55:  # write from a random alive node
                node = ring[rng.integers(0, len(ring))]
                key = rng.integers(0, 9, size=rng.integers(2, 6)).astype(np.int32)
                slots = node.pool.alloc(len(key))
                if slots is not None:
                    node.insert(key, slots)
                    inserted += 1
            elif roll < 0.70 and len(ring) > 3:  # hard crash
                victim = [a for a in RING_ADDRS if a not in c.dead]
                c.crash(victim[rng.integers(0, len(victim))])
            elif roll < 0.85 and c.dead:  # rejoin one dead node
                c.rejoin(sorted(c.dead)[rng.integers(0, len(c.dead))])
            else:
                time.sleep(float(rng.random()) * 0.3)
            if rng.random() < 0.5:
                time.sleep(0.05)
        assert inserted > 0, "storm produced no writes; widen the schedule"

        # Bring everyone back, then require full membership convergence.
        for addr in sorted(c.dead):
            c.rejoin(addr)
        everyone = c.alive_ring() + [c.router]
        want_ranks = {n.rank for n in c.alive_ring()}
        def views_converged():
            # Membership AND a single common epoch: an equal-epoch merge
            # bumps one node first and its announcement is in flight for a
            # moment, so both must be inside the wait.
            return all(
                {r for r in range(5) if n.view.contains(r)} == want_ranks
                for n in everyone
            ) and len({n.view.epoch for n in everyone}) == 1

        assert wait_for(views_converged, timeout=20), [
            (n.rank, n.view) for n in everyone
        ]

        # The re-formed ring replicates: one fresh insert reaches every
        # ring node and the router attributes it to the writer.
        writer = c.alive_ring()[int(rng.integers(0, 5))]
        key = np.array([7, 7, seed, 7], dtype=np.int32)
        slots = writer.pool.alloc(len(key))
        assert slots is not None
        writer.insert(key, slots)
        assert wait_for(
            lambda: all(
                n.tree.match_prefix(key, split_partial=False).length == len(key)
                for n in c.alive_ring()
            ),
            timeout=15,
        ), "post-storm insert did not replicate to every ring node"
        assert wait_for(
            lambda: c.router.match_prefix(key).match_len == len(key), timeout=10
        )
        route = c.router.match_prefix(key)
        assert route.prefill_rank >= 0 or route.decode_rank >= 0

        # Allocator safety on every survivor: force a GC round at the
        # origin of any pending dups; double frees raise inside.
        for n in c.alive_ring():
            n.run_gc_round()
        time.sleep(1.0)
        for n in c.alive_ring():
            tree_self_slots = []
            for tn in n.tree._all_nodes():
                v = tn.value
                if (
                    v is not None
                    and getattr(v, "rank", None) == n.rank
                    and hasattr(v, "indices")
                    and len(v)
                ):
                    tree_self_slots.append(v.indices)
            if tree_self_slots:
                flat = np.concatenate(tree_self_slots)
                assert n.pool.allocator.is_allocated(flat).all(), (
                    f"node {n.rank}: tree references freed slots"
                )
    finally:
        c.close()
