"""Prefix-ownership sharding (``cache/sharding.py``): ownership-map
derivation, per-shard tree fingerprints, the shard-summary/pull wire,
owner-addressed delivery on a live mesh, summary-based routing,
pull-through fills, owner-scoped repair, drain-time shard handoff, and
the ``replication_factor = 0`` full-replica compatibility contract."""

import time

import numpy as np
import pytest

from radixmesh_tpu.cache.radix_tree import RadixTree, root_page_hash
from radixmesh_tpu.cache.sharding import (
    NUM_SHARDS,
    OwnershipMap,
    ShardSummaryTable,
    build_ownership,
    decode_shard_summary,
    encode_shard_summary,
    shard_of_tokens,
)

# The lint (tests/test_mesh_lint.py::TestOwnershipSingleWriter) confines
# OwnershipMap construction to cache/sharding.py; tests go through
# build_ownership like every product module.
assert OwnershipMap is not None


def _shard_fn(page):
    return lambda key, _p=max(1, page): shard_of_tokens(key[:_p])


@pytest.mark.quick
class TestShardOf:
    def test_stable_and_in_range(self):
        key = [5, 17, 123, 9]
        assert shard_of_tokens(key) == shard_of_tokens(list(key))
        assert 0 <= shard_of_tokens(key) < NUM_SHARDS
        assert shard_of_tokens([]) == 0

    def test_depends_only_on_given_tokens(self):
        assert shard_of_tokens([1, 2]) == shard_of_tokens(
            np.asarray([1, 2], dtype=np.int32)
        )
        # Different first page → (almost surely) reachable different
        # shard: the space is actually partitioned.
        shards = {shard_of_tokens([t]) for t in range(500)}
        assert len(shards) == NUM_SHARDS


@pytest.mark.quick
class TestOwnershipMap:
    def test_deterministic_and_epoch_carried(self):
        a = build_ownership(range(10), 3, epoch=7)
        b = build_ownership(reversed(range(10)), 3, epoch=7)
        assert a.owners == b.owners
        assert a.epoch == 7 and a.rf == 3

    def test_rf_distinct_owners_every_shard(self):
        m = build_ownership(range(12), 3, 0)
        for sid in range(NUM_SHARDS):
            owners = m.owners_of(sid)
            assert len(owners) == 3
            assert len(set(owners)) == 3

    def test_n_below_rf_degeneracy(self):
        m = build_ownership([4, 9], 3, 0)
        for sid in range(NUM_SHARDS):
            assert set(m.owners_of(sid)) == {4, 9}

    def test_role_aware_owner_sets(self):
        """With a role split, every shard gets min(rf, role size) owners
        from EACH role (prefill listed first) — the per-role failover
        invariant (a decode crash must leave a surviving decode owner)."""
        is_prefill = lambda r: r < 3  # noqa: E731 — ranks 0-2 prefill, 3-6 decode
        m = build_ownership(range(7), 2, 0, is_prefill=is_prefill)
        for sid in range(NUM_SHARDS):
            owners = m.owners_of(sid)
            pf = [r for r in owners if is_prefill(r)]
            dc = [r for r in owners if not is_prefill(r)]
            assert len(pf) == 2 and len(dc) == 2
            assert owners[: len(pf)] == tuple(pf)  # prefill-first order

    def test_owned_shards_inverse(self):
        m = build_ownership(range(8), 3, 0)
        for rank in range(8):
            for sid in m.owned_shards(rank):
                assert m.is_owner(rank, sid)
        total = sum(len(m.owned_shards(r)) for r in range(8))
        assert total == 3 * NUM_SHARDS

    def test_membership_change_moves_bounded_shards(self):
        before = build_ownership(range(20), 3, 0)
        after = build_ownership(range(21), 3, 1)
        changed = sum(
            1
            for sid in range(NUM_SHARDS)
            if set(before.owners_of(sid)) != set(after.owners_of(sid))
        )
        # One joiner must not reshuffle the shard space (bounded key
        # movement is the consistent-hash property sharding rides).
        assert changed <= NUM_SHARDS // 3


@pytest.mark.quick
class TestShardSummaryWire:
    def test_round_trip(self):
        shards = {
            5: (0xDEADBEEF, [(123, 64), (456, 8)]),
            61: (0, []),
        }
        origin, back, loads = decode_shard_summary(
            encode_shard_summary(9, shards)
        )
        assert origin == 9
        assert back == shards
        assert loads == {}  # no heat trailer emitted

    def test_round_trip_with_heat_trailer(self):
        """PR 9: per-shard decayed loads ride the summary as an
        old-wire-tolerant trailer; a loadless encode stays bit-for-bit
        the pre-heat payload (compat asserted by byte equality)."""
        shards = {5: (0xDEADBEEF, [(123, 64)])}
        plain = encode_shard_summary(9, shards)
        heated = encode_shard_summary(9, shards, loads={5: 12.5, 7: 0.25})
        assert bytes(plain.tobytes()) == bytes(
            heated.tobytes()[: plain.nbytes]
        )
        origin, back, loads = decode_shard_summary(heated)
        assert origin == 9 and back == shards
        assert loads == {5: 12.5, 7: 0.25}
        # A pre-PR-9 peer parses exactly n_shards sections and ignores
        # the trailing bytes — so the v1 fields of the heated frame
        # decode identically to the plain frame's.
        assert decode_shard_summary(plain)[:2] == (origin, back)

    def test_root_budget_truncates(self):
        roots = [(i, 1000 - i) for i in range(1000)]
        _, back, _ = decode_shard_summary(
            encode_shard_summary(0, {3: (1, roots)})
        )
        from radixmesh_tpu.cache.sharding import MAX_SUMMARY_ROOTS

        assert len(back[3][1]) == MAX_SUMMARY_ROOTS

    def test_malformed_raises(self):
        with pytest.raises(ValueError):
            decode_shard_summary(np.asarray([1, 2, 3], dtype=np.int32))

    def test_table_lookup_and_retain(self):
        t = ShardSummaryTable()
        t.fold(1, {4: (11, [(99, 32)])})
        t.fold(2, {4: (11, [(99, 16)]), 5: (7, [])})
        assert t.lookup(4, 99) == {1: 32, 2: 16}
        assert t.shard_fp(2, 5) == 7
        t.retain([2])
        assert t.lookup(4, 99) == {2: 16}
        t.forget(2)
        assert t.lookup(4, 99) == {}


@pytest.mark.quick
class TestTreeShardFingerprints:
    def test_scalar_equals_xor_of_shards(self):
        t = RadixTree(page_size=1, shard_fn=_shard_fn(1))
        rng = np.random.default_rng(0)
        for i in range(30):
            key = rng.integers(1, 500, size=12)
            t.insert(key, np.arange(12, dtype=np.int32) + i * 12)
        acc = 0
        for fp in t.shard_fingerprints().values():
            acc ^= fp
        assert acc == t.fingerprint_

    def test_order_and_split_invariance(self):
        keys = [
            np.asarray([1, 2, 3, 4, 5, 6], dtype=np.int32),
            np.asarray([1, 2, 3, 9, 9, 9], dtype=np.int32),
            np.asarray([7, 7, 7, 7, 7, 7], dtype=np.int32),
        ]
        a = RadixTree(page_size=1, shard_fn=_shard_fn(1))
        b = RadixTree(page_size=1, shard_fn=_shard_fn(1))
        for k in keys:
            a.insert(k, np.arange(len(k), dtype=np.int32))
        for k in reversed(keys):
            b.insert(k, np.arange(len(k), dtype=np.int32))
        assert a.shard_fingerprints() == b.shard_fingerprints()

    def test_evict_and_delete_fold_out(self):
        t = RadixTree(page_size=1, shard_fn=_shard_fn(1))
        key = np.asarray([3, 1, 4, 1, 5], dtype=np.int32)
        t.insert(key, np.arange(5, dtype=np.int32))
        assert t.shard_fingerprints()
        t.evict(100)
        assert t.shard_fingerprints() == {}
        assert t.fingerprint_ == 0

    def test_nodes_in_shard_and_root_summaries(self):
        page = 4
        t = RadixTree(page_size=page, shard_fn=_shard_fn(page))
        key = np.arange(100, 116, dtype=np.int32)
        ext = np.concatenate([key[:8], np.arange(200, 208, dtype=np.int32)])
        t.insert(key, np.arange(16, dtype=np.int32))
        t.insert(ext, np.arange(16, dtype=np.int32))
        sid = shard_of_tokens(key[:page])
        nodes = t.nodes_in_shard(sid)
        assert nodes and all(n.shard == sid for n in nodes)
        roots = t.shard_root_summaries(sid)
        assert roots == [(root_page_hash(key, page), 16)]

    def test_shard_constant_down_subtree_across_splits(self):
        t = RadixTree(page_size=1, shard_fn=_shard_fn(1))
        base = np.asarray([42, 1, 2, 3, 4, 5, 6, 7], dtype=np.int32)
        t.insert(base, np.arange(8, dtype=np.int32))
        fork = np.concatenate([base[:4], [9, 9]]).astype(np.int32)
        t.insert(fork, np.arange(6, dtype=np.int32))  # splits mid-node
        sid = shard_of_tokens(base[:1])
        assert set(t.shard_fingerprints()) == {sid}
        for n in t.nodes_in_shard(sid):
            assert n.shard == sid


@pytest.mark.quick
class TestRepairShardWire:
    def test_probe_round_trip_and_discrimination(self):
        from radixmesh_tpu.cache.repair_plane import (
            decode_shard_probe,
            encode_probe,
            encode_shard_probe,
            is_shard_frame,
        )

        pairs = [(3, 0xAB), (17, 0)]
        arr = encode_shard_probe(pairs)
        assert is_shard_frame(arr)
        assert decode_shard_probe(arr) == sorted(pairs)
        vec = np.zeros(64, dtype="<u8")
        assert not is_shard_frame(encode_probe(vec))

    def test_session_summary_round_trip(self):
        from radixmesh_tpu.cache.repair_plane import (
            decode_shard_session_summary,
            encode_shard_session_summary,
            is_shard_frame,
        )

        pairs = [(5, 123), (6, 456)]
        hashes = {111, 222}
        arr = encode_shard_session_summary(pairs, hashes, reply=True)
        assert is_shard_frame(arr)
        back_pairs, back_hashes, reply = decode_shard_session_summary(arr)
        assert back_pairs == pairs and back_hashes == hashes and reply


def _mesh_cluster(rf, n_prefill=3, n_decode=2, router=True, **cfg_kw):
    from radixmesh_tpu.cache.mesh_cache import MeshCache
    from radixmesh_tpu.comm.inproc import InprocHub
    from radixmesh_tpu.config import MeshConfig

    InprocHub.reset_default()
    prefill = [f"tp{i}" for i in range(n_prefill)]
    decode = [f"td{i}" for i in range(n_decode)]
    routers = ["tr0"] if router else []

    def cfg(addr):
        return MeshConfig(
            prefill_nodes=prefill,
            decode_nodes=decode,
            router_nodes=routers,
            local_addr=addr,
            protocol="inproc",
            replication_factor=rf,
            tick_interval_s=0.05,
            failure_timeout_s=30.0,
            shard_summary_interval_s=0.05,
            **cfg_kw,
        )

    nodes = [MeshCache(cfg(a)) for a in prefill + decode]
    rm = MeshCache(cfg("tr0")) if router else None
    all_nodes = nodes + ([rm] if rm else [])
    for n in all_nodes:
        n.start()
    for n in all_nodes:
        assert n.wait_ready(timeout=10)
    return nodes, rm


def _close_all(nodes, rm):
    for n in nodes + ([rm] if rm else []):
        n.close()


def _wait(pred, timeout=8.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class TestShardedMeshLive:
    def test_insert_delivered_to_owner_set_only(self):
        nodes, rm = _mesh_cluster(rf=3)
        try:
            key = list(range(300, 332))
            w = nodes[0]
            w.insert(key, np.arange(32, dtype=np.int32))
            owners = w.owner_ranks(key)
            assert len(owners) >= 3  # role-aware: rf per serving role
            assert _wait(
                lambda: all(
                    nodes[r].match_prefix(key).length == 32 for r in owners
                )
            )
            for r in range(len(nodes)):
                if r not in owners and r != w.rank:
                    assert nodes[r].match_prefix(key).length == 0, (
                        f"non-owner rank {r} received an owner-addressed insert"
                    )
            # Router holds NO tree replica under sharding.
            assert rm.tree.evictable_size_ + rm.tree.protected_size_ == 0
            # Telemetry: bytes-per-insert EWMA moved, owned shards gauge set.
            assert w._bpi_ewma > 0
            assert len(w.ownership.owned_shards(w.rank)) > 0
        finally:
            _close_all(nodes, rm)

    def test_router_routes_from_summaries_to_owner(self):
        nodes, rm = _mesh_cluster(rf=3)
        try:
            key = list(range(700, 732))
            nodes[1].insert(key, np.arange(32, dtype=np.int32))
            owners = set(nodes[1].owner_ranks(key)) | {nodes[1].rank}
            assert _wait(lambda: rm.shard_route(key).match_len > 0)
            m = rm.shard_route(key)
            assert m.match_len == 32
            assert m.prefill_rank in owners or m.decode_rank in owners
        finally:
            _close_all(nodes, rm)

    def test_pull_through_fills_non_owner(self):
        nodes, rm = _mesh_cluster(rf=2)
        try:
            key = list(range(40, 72))
            w = nodes[0]
            w.insert(key, np.arange(32, dtype=np.int32))
            owners = w.owner_ranks(key)
            non_owners = [
                r for r in range(len(nodes))
                if r not in owners and r != w.rank
            ]
            if not non_owners:
                pytest.skip("rf=2 owner set covered every node")
            tgt = non_owners[0]
            donor = [r for r in owners if r != tgt][0]
            assert _wait(
                lambda: nodes[donor].match_prefix(key).length == 32
            )
            assert rm.send_shard_pull(key, donor, tgt)
            assert _wait(
                lambda: nodes[tgt].match_prefix(key).length == 32
            ), "pull-through never filled the target replica"
        finally:
            _close_all(nodes, rm)

    def test_owner_scoped_repair_heals_diverged_shard(self):
        from radixmesh_tpu.cache.repair_plane import RepairConfig, RepairPlane

        nodes, rm = _mesh_cluster(rf=2)
        planes = [
            RepairPlane(
                n,
                RepairConfig(
                    interval_s=0.05, age_threshold_s=0.0,
                    backoff_base_s=0.05,
                ),
            ).start()
            for n in nodes
        ]
        try:
            rng = np.random.default_rng(1)
            keys = [rng.integers(1, 900, size=24).tolist() for _ in range(5)]
            for i, k in enumerate(keys):
                nodes[0].insert(k, np.arange(24, dtype=np.int32) + i * 24)
            k = keys[0]
            owners = nodes[0].owner_ranks(k)
            victim = next((r for r in owners if r != 0), owners[0])
            vn = nodes[victim]
            assert _wait(lambda: vn.match_prefix(k).length == 24)
            with vn._lock:
                vn._apply_delete(np.asarray(k, dtype=np.int32))
            assert vn.match_prefix(k).length == 0
            assert _wait(
                lambda: vn.match_prefix(k).length == 24, timeout=12.0
            ), "owner-scoped repair never resurrected the dropped entry"
            assert _wait(
                lambda: nodes[0].fleet.shard_convergence()["converged"],
                timeout=12.0,
            )
        finally:
            for p in planes:
                p.close()
            _close_all(nodes, rm)

    def test_drain_handoff_moves_owned_shards(self):
        nodes, rm = _mesh_cluster(rf=1, n_prefill=4, n_decode=0, router=False)
        try:
            rng = np.random.default_rng(5)
            w = nodes[0]
            keys = []
            # Keys OWNED by rank 0 (rf=1 per role: exactly one owner).
            while len(keys) < 4:
                k = rng.integers(1, 900, size=16).tolist()
                if w.owner_ranks(k) == (0,):
                    keys.append(k)
                    w.insert(k, np.arange(16, dtype=np.int32))
            stats = w.handoff_owned_shards()
            assert stats["shards"] > 0 and stats["entries"] > 0
            # The would-be successor owners receive the entries.
            survivors = [r for r in range(1, 4)]
            future = build_ownership(
                survivors, 1, 99, is_prefill=w.cfg.is_prefill_rank
            )
            for k in keys:
                sid = shard_of_tokens(np.asarray(k[:1], dtype=np.int32))
                new_owner = future.owners_of(sid)[0]
                assert _wait(
                    lambda k=k, r=new_owner: nodes[r].match_prefix(k).length
                    == 16
                ), "handoff never reached the new owner"
        finally:
            _close_all(nodes, None)

    def test_ownership_rebuilds_on_view_change(self):
        nodes, rm = _mesh_cluster(rf=2, n_prefill=3, n_decode=2)
        try:
            w = nodes[0]
            epoch0 = w.ownership.epoch
            with w._lock:
                old = w.view
                w.view = old.without(nodes[-1].rank)
                w._after_view_change(old)
            assert w.ownership.epoch == w.view.epoch != epoch0
            assert nodes[-1].rank not in w.ownership.ranks
        finally:
            _close_all(nodes, rm)


class TestFullReplicaCompat:
    """``--replication-factor 0``: bit-for-bit the old wire behavior."""

    def test_rf0_mesh_is_unsharded(self):
        from radixmesh_tpu.cache.mesh_cache import MeshCache
        from radixmesh_tpu.config import MeshConfig

        mesh = MeshCache(MeshConfig(
            prefill_nodes=["a", "b"], decode_nodes=[], router_nodes=[],
            local_addr="a", protocol="inproc",
        ))
        assert not mesh.sharded
        assert mesh.ownership is None
        assert mesh._shard_table is None
        assert mesh.tree.shard_fn is None
        assert mesh.owner_ranks([1, 2, 3]) == ()

    def test_rf0_insert_rides_the_ring_to_everyone(self):
        nodes, rm = _mesh_cluster(rf=0)
        try:
            key = list(range(10, 42))
            nodes[0].insert(key, np.arange(32, dtype=np.int32))
            assert _wait(
                lambda: all(
                    n.match_prefix(key).length == 32 for n in nodes
                )
            ), "full-replica insert did not reach every ring member"
            # The router replica fills too (master fan-out), exactly the
            # pre-sharding contract.
            assert _wait(lambda: rm.match_prefix(key).match_len == 32)
        finally:
            _close_all(nodes, rm)

    def test_rf0_emits_ring_ttl_frames(self):
        """The wire frame of an rf=0 insert carries a FULL ring-lap TTL
        (not the sharded point-to-point ttl=1): the frame bytes are the
        pre-sharding wire, bit-for-bit."""
        from radixmesh_tpu.cache.mesh_cache import MeshCache
        from radixmesh_tpu.cache.oplog import deserialize
        from radixmesh_tpu.config import MeshConfig

        sent = []
        mesh = MeshCache(MeshConfig(
            prefill_nodes=["a", "b", "c"], decode_nodes=[],
            router_nodes=[], local_addr="a", protocol="inproc",
        ))
        mesh._started = True
        mesh._send_bytes = lambda data, control=False, dest="ring": sent.append(
            data
        )
        mesh.insert([1, 2, 3], np.arange(3, dtype=np.int32))
        assert len(sent) == 1
        op = deserialize(sent[0])
        assert op.ttl == 3  # one full lap of the 3-ring

    def test_rf_requires_flat_ring(self):
        from radixmesh_tpu.config import MeshConfig

        with pytest.raises(ValueError, match="topology: ring"):
            MeshConfig(
                prefill_nodes=[f"h{i}" for i in range(6)],
                decode_nodes=[], router_nodes=[], local_addr="h0",
                topology="hier", replication_factor=3,
            ).validate()


@pytest.mark.quick
class TestBootstrapConvergence:
    def test_sharded_bootstrap_requires_summaries(self):
        from radixmesh_tpu.cache.mesh_cache import MeshCache
        from radixmesh_tpu.config import MeshConfig

        def mk(addr):
            return MeshCache(MeshConfig(
                prefill_nodes=["ba", "bb"], decode_nodes=[],
                router_nodes=[], local_addr=addr, protocol="inproc",
                replication_factor=2,
            ))

        a, b = mk("ba"), mk("bb")
        # No gossip from b yet: not converged (silence != convergence).
        assert not a.bootstrap_converged_with(b.rank)
        # Empty-tree summaries from b: both replicas empty → converged.
        a.fleet.fold_shard_fps(
            b.rank,
            {sid: 0 for sid in b.ownership.owned_shards(b.rank)},
        )
        assert a.bootstrap_converged_with(b.rank)
        # b advertises data a lacks in a co-owned shard → diverged.
        sid = next(
            s for s in a.ownership.owned_shards(a.rank)
            if a.ownership.is_owner(b.rank, s)
        )
        fps = {s: 0 for s in b.ownership.owned_shards(b.rank)}
        fps[sid] = 12345
        a.fleet.fold_shard_fps(b.rank, fps)
        assert not a.bootstrap_converged_with(b.rank)
        assert a.diverged_shards_with(b.rank) == [sid]


@pytest.mark.quick
class TestShardHeat:
    """PR 9 leg (b): decayed per-shard traffic counters — the
    rebalancer's measurement substrate (single-writer: only
    cache/mesh_cache.py calls the note_* sites; test_mesh_lint pins
    it)."""

    def test_decay_halves_per_half_life(self):
        from radixmesh_tpu.cache.sharding import ShardHeat

        clock = {"t": 0.0}
        h = ShardHeat(half_life_s=10.0, now=lambda: clock["t"])
        h.note_insert(3, 100)
        assert h.loads()[3] == pytest.approx(10.0)  # 100 tok / 10 s window
        clock["t"] = 10.0
        assert h.loads()[3] == pytest.approx(5.0)  # one half-life later
        clock["t"] = 30.0
        assert h.loads()[3] == pytest.approx(1.25)
        # New traffic decays the old value first, then adds.
        h.note_insert(3, 100)
        assert h.loads()[3] == pytest.approx(11.25)

    def test_kinds_tracked_separately_and_loads_combine_insert_hit(self):
        from radixmesh_tpu.cache.sharding import ShardHeat

        h = ShardHeat(half_life_s=10.0, now=lambda: 5.0)
        h.note_insert(1, 40, nbytes=512)
        h.note_hit(1, 60)
        h.note_pull(1)
        snap = h.snapshot()[1]
        assert snap["insert_tokens"] == pytest.approx(40.0)
        assert snap["hit_tokens"] == pytest.approx(60.0)
        assert snap["pull_throughs"] == pytest.approx(1.0)
        assert snap["bytes"] == pytest.approx(512.0)
        assert h.loads()[1] == pytest.approx(10.0)  # (40+60)/10

    def test_mesh_counts_insert_hit_and_reports_heat(self):
        """Single-node seam: insert() and match_prefix() on a sharded
        P/D mesh feed the heat tracker; broadcast_shard_summary folds
        the loads into the local FleetView and shard_heat_report names
        the hot shard + its owner set."""
        from radixmesh_tpu.cache.mesh_cache import MeshCache
        from radixmesh_tpu.config import MeshConfig

        mesh = MeshCache(MeshConfig(
            prefill_nodes=["hp0", "hp1"], decode_nodes=[], router_nodes=[],
            local_addr="hp0", protocol="inproc", replication_factor=1,
        ))
        try:
            rng = np.random.default_rng(5)
            hot_key = None
            for _ in range(64):
                key = rng.integers(1, 50_000, size=8).astype(np.int32)
                sid = shard_of_tokens(key[:1])
                if mesh.ownership.is_owner(mesh.rank, sid):
                    hot_key = key
                    break
            assert hot_key is not None
            hot_sid = shard_of_tokens(hot_key[:1])
            for _ in range(10):
                mesh.insert(hot_key, np.arange(8, dtype=np.int32))
                mesh.match_prefix(hot_key)
            assert mesh.heat.loads().get(hot_sid, 0.0) > 0.0
            assert mesh.broadcast_shard_summary() > 0
            report = mesh.shard_heat_report()
            assert report["hot_shard"] == hot_sid
            assert report["hot_owners"] == list(
                mesh.ownership.owners_of(hot_sid)
            )
            assert report["skew_score"] >= 1.0
            assert report["reporters"] == 1
        finally:
            mesh.close()

    def test_cooled_shard_zeroes_its_gauge_and_leaves_gossip(self):
        """A scraped gauge has no whole-summary swap: a shard that cools
        to (effectively) zero must export 0 — not its last hot value —
        and must leave the heat trailer entirely (MIN_LOAD floor), so
        the fleet map's empty-fold clears the reporter."""
        from radixmesh_tpu.cache.mesh_cache import MeshCache
        from radixmesh_tpu.config import MeshConfig
        from radixmesh_tpu.obs.metrics import get_registry

        clock = {"t": 0.0}
        mesh = MeshCache(MeshConfig(
            prefill_nodes=["zp0", "zp1"], decode_nodes=[], router_nodes=[],
            local_addr="zp0", protocol="inproc", replication_factor=1,
        ))
        try:
            mesh.heat._now = lambda: clock["t"]
            rng = np.random.default_rng(5)
            key = next(
                k for k in (
                    rng.integers(1, 50_000, size=8).astype(np.int32)
                    for _ in range(64)
                )
                if mesh.ownership.is_owner(0, shard_of_tokens(k[:1]))
            )
            sid = shard_of_tokens(key[:1])
            mesh.insert(key, np.arange(8, dtype=np.int32))
            mesh.broadcast_shard_summary()
            gauge = (
                'radixmesh_shard_heat_tokens_per_second'
                f'{{node="prefill@0",shard="{sid}"}}'
            )
            assert get_registry().snapshot()[gauge] > 0
            clock["t"] = 10_000.0  # many half-lives: fully cooled
            assert mesh.heat.loads() == {}
            mesh.broadcast_shard_summary()
            assert get_registry().snapshot()[gauge] == 0.0
            assert mesh.fleet.shard_heat()["reporters"] == 0
        finally:
            mesh.close()

    def test_unsharded_and_router_nodes_have_no_heat(self):
        from radixmesh_tpu.cache.mesh_cache import MeshCache
        from radixmesh_tpu.config import MeshConfig

        plain = MeshCache(MeshConfig(
            prefill_nodes=["up0", "up1"], decode_nodes=[], router_nodes=[],
            local_addr="up0", protocol="inproc",
        ))
        router = MeshCache(MeshConfig(
            prefill_nodes=["up2", "up3"], decode_nodes=[],
            router_nodes=["ur0"], local_addr="ur0", protocol="inproc",
            replication_factor=1,
        ))
        try:
            assert plain.heat is None  # rf=0: no shard space to attribute
            assert router.heat is None  # routers read the map, never write
        finally:
            plain.close()
            router.close()
