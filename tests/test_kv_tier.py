"""Durable KV spill tier (``cache/kv_tier.py``): extent-file crash
discipline (commit-by-rename, checksum-verified reads, torn tails and
bit flips dropped — never served), the three-tier radix walk, write-
behind destage + demote-over-drop eviction, cold-cell resurrection,
and byte-identical resume after a whole-cell kill. Every scratch dir is
a pytest ``tmp_path`` (nothing lands in the repo tree)."""

import glob
import os
import threading
import time

import jax
import numpy as np
import pytest

from radixmesh_tpu.cache.kv_tier import (
    EXTENT_SCHEMA_VERSION,
    DiskKVTier,
    ExtentRef,
    node_heat,
)
from radixmesh_tpu.cache.radix_tree import RadixTree, TreeNode
from radixmesh_tpu.engine.engine import Engine
from radixmesh_tpu.engine.request import RequestState, SamplingParams
from radixmesh_tpu.models.llama import ModelConfig, init_params

PAGE = 4


@pytest.fixture(scope="module")
def tiny():
    cfg = ModelConfig.tiny()
    return cfg, init_params(cfg, jax.random.PRNGKey(0))


def make_engine(tiny, tier_dir, **kw):
    cfg, params = tiny
    kw.setdefault("num_slots", 1024)
    kw.setdefault("page_size", PAGE)
    kw.setdefault("max_batch", 3)
    kw.setdefault("host_cache_slots", 512)
    kw.setdefault("kv_tier_watermark", 0.0)
    kw.setdefault("kv_tier_destage_budget", 64)
    kw.setdefault("kv_tier_destage_interval_s", 0.0)
    kw.setdefault("kv_transfer_chunk_tokens", 32)
    return Engine(cfg, params, kv_tier_dir=str(tier_dir), **kw)


def settle(eng, timeout=15.0):
    """Pump until every spill committed (the destager's engine half)."""
    plane = eng.kv_transfer
    plane.wait_host_ready()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        eng.step()
        if plane.spills_idle():
            return
        plane.wait_progress(0.01)
    raise AssertionError("spills never settled")


def spill_everything(eng, prompts, sampling):
    """Serve, push device -> host, destage host -> disk, commit."""
    for p in prompts:
        eng.generate([list(p)], sampling)
    eng.tree.evict(1 << 20)
    eng.kv_transfer.wait_host_ready()
    eng.tree.destage_cold(force=True, budget=1 << 20)
    settle(eng)


# ---------------------------------------------------------------------------
# extent format: commit discipline + corruption property tests
# ---------------------------------------------------------------------------


@pytest.mark.quick
class TestExtentFormat:
    def _tier(self, tmp_path, **kw):
        kw.setdefault("page_size", PAGE)
        return DiskKVTier(str(tmp_path / "tier"), name="fmt", **kw)

    def _payload(self, n=8, seed=0):
        rng = np.random.default_rng(seed)
        prefix = rng.integers(1, 100, size=12).astype(np.int32)
        seg = rng.integers(1, 100, size=n).astype(np.int32)
        kv = rng.standard_normal((2, 2, n, 2, 4)).astype(np.float32)
        return prefix, seg, kv

    def test_write_read_roundtrip(self, tmp_path):
        tier = self._tier(tmp_path)
        prefix, seg, kv = self._payload()
        ref = tier.write_extent(prefix, seg, kv, None)
        assert ref is not None and len(ref) == len(seg)
        got, scales = tier.read_extent(ref)
        assert scales is None
        np.testing.assert_array_equal(got, kv)

    def test_quant_scales_roundtrip(self, tmp_path):
        tier = self._tier(tmp_path)
        prefix, seg, _ = self._payload()
        rng = np.random.default_rng(1)
        kv = rng.integers(-128, 127, size=(2, 2, 8, 2, 4)).astype(np.int8)
        scales = rng.standard_normal((2, 2, 8, 2)).astype(np.float32)
        ref = tier.write_extent(prefix, seg, kv, scales)
        got, got_s = tier.read_extent(ref)
        np.testing.assert_array_equal(got, kv)
        np.testing.assert_array_equal(got_s, scales)

    def test_same_path_respill_replaces_not_duplicates(self, tmp_path):
        tier = self._tier(tmp_path)
        prefix, seg, kv = self._payload()
        tier.write_extent(prefix, seg, kv, None)
        tier.write_extent(prefix, seg, kv * 2, None)
        assert tier.extents == 1

    def test_truncation_anywhere_is_detected_never_served(self, tmp_path):
        """Property: a committed extent truncated at ANY offset reads as
        None (counted), and the file is dropped — the torn-tail rule."""
        rng = np.random.default_rng(2)
        for trial in range(12):
            tier = self._tier(tmp_path / f"t{trial}")
            prefix, seg, kv = self._payload(seed=trial)
            ref = tier.write_extent(prefix, seg, kv, None)
            size = os.path.getsize(ref.path)
            cut = int(rng.integers(0, size))
            with open(ref.path, "r+b") as fh:
                fh.truncate(cut)
            assert tier.read_extent(ref) is None
            assert not os.path.exists(ref.path)

    def test_bitflip_anywhere_is_detected_never_served(self, tmp_path):
        """Property: one flipped bit at ANY byte offset — preamble,
        header, tokens, or KV payload — fails verification."""
        rng = np.random.default_rng(3)
        for trial in range(16):
            tier = self._tier(tmp_path / f"b{trial}")
            prefix, seg, kv = self._payload(seed=100 + trial)
            ref = tier.write_extent(prefix, seg, kv, None)
            size = os.path.getsize(ref.path)
            off = int(rng.integers(0, size))
            with open(ref.path, "r+b") as fh:
                fh.seek(off)
                b = fh.read(1)
                fh.seek(off)
                fh.write(bytes([b[0] ^ (1 << int(rng.integers(0, 8)))]))
            assert tier.read_extent(ref) is None

    def test_future_schema_refused(self, tmp_path):
        tier = self._tier(tmp_path)
        prefix, seg, kv = self._payload()
        ref = tier.write_extent(prefix, seg, kv, None)
        with open(ref.path, "r+b") as fh:
            raw = bytearray(fh.read())
            # Preamble: magic(4) schema(H at offset 4).
            raw[4:6] = (EXTENT_SCHEMA_VERSION + 1).to_bytes(2, "little")
            fh.seek(0)
            fh.write(bytes(raw))
        assert tier.read_extent(ref) is None

    def test_crash_mid_spill_leaves_committed_extents_readable(self, tmp_path):
        """kill -9 mid-write = a leftover temp file; the rename is the
        commit point, so every committed extent scans clean and the
        torn temp is removed, never grafted."""
        tier = self._tier(tmp_path)
        prefix, seg, kv = self._payload()
        tier.write_extent(prefix, seg, kv, None)
        torn = os.path.join(tier.dir, "ext-dead.kv.tmp.12345")
        with open(torn, "wb") as fh:
            fh.write(b"half-written garbage")
        tier2 = DiskKVTier(tier.dir, page_size=PAGE, name="fmt2")
        metas = tier2.scan()
        assert len(metas) == 1
        np.testing.assert_array_equal(metas[0].seg_tokens, seg)
        assert not os.path.exists(torn)
        got, _ = tier2.read_extent(metas[0].ref)
        np.testing.assert_array_equal(got, kv)

    def test_capacity_drops_oldest_and_counts(self, tmp_path):
        tier = self._tier(tmp_path, capacity_bytes=1)
        p1 = self._payload(seed=10)
        p2 = self._payload(seed=11)
        tier.write_extent(*p1, None)
        tier.write_extent(*p2, None)
        # Over a 1-byte budget only the newest (protected) write stays.
        assert tier.extents == 1
        assert any(m[2] == "drop" for m in tier.recent_moves)

    def test_retire_is_in_memory_until_drained(self, tmp_path):
        tier = self._tier(tmp_path)
        prefix, seg, kv = self._payload()
        ref = tier.write_extent(prefix, seg, kv, None)
        tier.retire(ref)
        assert os.path.exists(ref.path)  # engine-thread safe: no unlink
        assert tier.drain_retired() == 1
        assert not os.path.exists(ref.path)

    def test_node_heat_decays(self):
        n = TreeNode()
        n.hit_count = 8
        n.last_access_time = 100.0
        assert node_heat(n, 100.0, half_life_s=10.0) == pytest.approx(8.0)
        assert node_heat(n, 110.0, half_life_s=10.0) == pytest.approx(4.0)
        assert node_heat(n, 200.0, half_life_s=10.0) < 0.01


# ---------------------------------------------------------------------------
# three-tier radix walk
# ---------------------------------------------------------------------------


def _ref(n):
    return ExtentRef(path=f"/fake/{n}", n_seg=n, nbytes=1, shard=0)


@pytest.mark.quick
class TestTierWalk:
    def _tree(self):
        t = RadixTree(page_size=1)
        return t

    def test_disk_extension_returned_in_order(self):
        t = self._tree()
        t.insert([1, 2, 3, 4, 5, 6], np.arange(6, dtype=np.int32))
        # device prefix [1,2] -> host [3,4] -> disk [5,6]
        node = t.root.children[1]
        a = t._split_node(node, 2)
        mid = a.children[self._ck(t, a)]
        b = t._split_node(mid, 2)
        leaf = b.children[self._ck(t, b)]
        mid_n = b
        mid_n.host_value = np.asarray(mid_n.value)
        mid_n.value = None
        leaf.disk_value = _ref(2)
        leaf.value = None
        m = t.match_prefix([1, 2, 3, 4, 5, 6])
        assert m.length == 2
        assert m.host_length == 2
        assert m.disk_length == 2
        assert [n is leaf for n in m.disk_nodes] == [True]
        assert m.restorable_nodes() == [mid_n, leaf]

    @staticmethod
    def _ck(tree, node):
        (k,) = node.children.keys()
        return k

    def test_host_below_disk_breaks_the_walk(self):
        t = self._tree()
        t.insert([1, 2, 3, 4], np.arange(4, dtype=np.int32))
        node = t.root.children[1]
        a = t._split_node(node, 2)
        deep = a.children[self._ck(t, a)]
        a.value = None
        a.disk_value = _ref(2)  # disk-resident interior
        deep.host_value = np.asarray(deep.value)
        deep.value = None  # host below disk: not prefix-closed
        m = t.match_prefix([1, 2, 3, 4])
        assert m.disk_length == 2 and m.host_length == 0

    def test_partial_disk_match_never_splits(self):
        t = self._tree()
        t.insert([1, 2, 3, 4], np.arange(4, dtype=np.int32))
        node = t.root.children[1]
        node.disk_value = _ref(4)
        node.value = None
        n_before = sum(1 for _ in t._all_nodes())
        m = t.match_prefix([1, 2, 9, 9])  # diverges mid-extent
        assert m.disk_length == 0
        assert sum(1 for _ in t._all_nodes()) == n_before

    def test_split_detaches_extent_via_hook(self):
        t = self._tree()
        retired = []
        t.on_disk_detach = retired.append
        t.insert([1, 2, 3, 4], np.arange(4, dtype=np.int32))
        node = t.root.children[1]
        ref = _ref(4)
        node.disk_value = ref
        t._split_node(node, 2)
        assert retired == [ref]
        assert node.disk_value is None

    def test_remove_node_retires_extents(self):
        t = self._tree()
        retired = []
        t.on_disk_detach = retired.append
        t.insert([1, 2, 3, 4], np.arange(4, dtype=np.int32))
        node = t.root.children[1]
        node.disk_value = _ref(4)
        node.value = None
        t._remove_node(node, [])
        assert len(retired) == 1

    def test_reset_retires_extents(self):
        t = self._tree()
        retired = []
        t.on_disk_detach = retired.append
        t.insert([1, 2, 3, 4], np.arange(4, dtype=np.int32))
        t.root.children[1].disk_value = _ref(4)
        t.reset()
        assert len(retired) == 1


# ---------------------------------------------------------------------------
# engine integration: spill / demote / restore / resurrect / resume
# ---------------------------------------------------------------------------


class TestEngineTier:
    def _prompts(self, cfg, n, tokens, seed=0):
        rng = np.random.default_rng(seed)
        return [
            rng.integers(1, cfg.vocab_size - 1, size=tokens).astype(np.int32)
            for _ in range(n)
        ]

    def test_tier_requires_host_cache(self, tiny, tmp_path):
        cfg, params = tiny
        with pytest.raises(ValueError, match="host tier"):
            Engine(cfg, params, kv_tier_dir=str(tmp_path / "d"),
                   host_cache_slots=0)

    def test_tier_auto_arms_the_plane(self, tiny, tmp_path):
        eng = make_engine(tiny, tmp_path / "arm", kv_transfer_async=False)
        assert eng.kv_transfer is not None
        assert eng.tree.disk is not None
        eng.kv_transfer.close()

    def test_spill_kill_resurrect_serves_from_disk(self, tiny, tmp_path):
        cfg, params = tiny
        d = tmp_path / "cell"
        prompts = self._prompts(cfg, 3, 96)
        samp = SamplingParams(temperature=0.0, max_new_tokens=2)
        eng = make_engine(tiny, d)
        spill_everything(eng, prompts, samp)
        assert eng._kv_tier.extents >= 3
        eng.kv_transfer.close()  # the whole cell dies: no flush
        del eng

        eng2 = make_engine(tiny, d)
        assert eng2.resurrected["grafted_nodes"] >= 3
        m = eng2.tree.match_prefix(prompts[0])
        assert m.disk_length > 0 and m.length == 0 and m.host_length == 0
        c0 = eng2.stats.cached_tokens
        eng2.generate([list(prompts[0])], samp)
        assert eng2.stats.cached_tokens - c0 > 0  # served from disk
        eng2.kv_transfer.close()

    def test_corrupt_extent_degrades_to_shorter_verified_prefix(
        self, tiny, tmp_path
    ):
        cfg, params = tiny
        d = tmp_path / "corrupt"
        prompts = self._prompts(cfg, 2, 96, seed=7)
        samp = SamplingParams(temperature=0.0, max_new_tokens=2)
        eng = make_engine(tiny, d)
        spill_everything(eng, prompts, samp)
        eng.kv_transfer.close()
        del eng
        files = sorted(glob.glob(str(d / "ext-*.kv")))
        with open(files[0], "r+b") as fh:
            fh.seek(os.path.getsize(files[0]) // 2)
            b = fh.read(1)
            fh.seek(-1, 1)
            fh.write(bytes([b[0] ^ 0xFF]))
        eng2 = make_engine(tiny, d)
        # The corrupt extent was dropped at scan; the survivor grafted.
        corrupt = sum(
            int(m.value) for m in eng2._kv_tier._m_corrupt_by.values()
        )
        assert corrupt >= 1
        # Both prompts still SERVE (one recomputes, one hits disk) and
        # nothing raises — corrupt KV never reaches the pool.
        for p in prompts:
            eng2.generate([list(p)], samp)
        eng2.kv_transfer.close()

    def test_eviction_prefers_demote_over_drop(self, tiny, tmp_path):
        """A disk-backed host copy frees its arena slots WITHOUT the
        node dying; an unbacked one dies — demote-over-drop."""
        cfg, params = tiny
        prompts = self._prompts(cfg, 2, 64, seed=3)
        samp = SamplingParams(temperature=0.0, max_new_tokens=2)
        eng = make_engine(tiny, tmp_path / "demote")
        spill_everything(eng, prompts, samp)
        dropped0 = eng.tree._m_host_evicted.value
        freed = eng.tree._evict_host(1 << 20)
        assert freed > 0
        # Demotes, not drops: the host-evicted (died) counter is flat
        # and every prefix still matches through its extent.
        assert eng.tree._m_host_evicted.value == dropped0
        for p in prompts:
            m = eng.tree.match_prefix(p)
            assert m.disk_length > 0
        eng.kv_transfer.close()

    def test_destage_min_heat_lets_cold_nodes_die(self, tiny, tmp_path):
        cfg, params = tiny
        prompts = self._prompts(cfg, 2, 64, seed=4)
        samp = SamplingParams(temperature=0.0, max_new_tokens=2)
        eng = make_engine(
            tiny, tmp_path / "cold", kv_tier_min_heat=1e9,
        )
        for p in prompts:
            eng.generate([list(p)], samp)
        eng.tree.evict(1 << 20)
        eng.kv_transfer.wait_host_ready()
        # Non-forced destage respects the heat floor: nothing qualifies.
        assert eng.tree.destage_cold(
            watermark=0.0, min_heat=1e9, budget=64
        ) == 0
        # The drain path is forced: durability wins over heat.
        assert eng.tree.destage_cold(force=True, budget=64) > 0
        eng.kv_transfer.close()

    def test_parked_disk_restore_while_decode_steps(self, tiny, tmp_path):
        cfg, params = tiny
        prompts = self._prompts(cfg, 2, 96, seed=5)
        samp = SamplingParams(temperature=0.0, max_new_tokens=2)
        eng = make_engine(tiny, tmp_path / "park")
        spill_everything(eng, prompts, samp)
        eng.tree._evict_host(1 << 20)  # disk-only residency
        bg = eng.add_request(
            list(self._prompts(cfg, 1, 32, seed=6)[0]),
            SamplingParams(temperature=0.0, max_new_tokens=32),
        )
        eng.step()
        req = eng.add_request(list(prompts[0]), samp)
        parked = False
        decode_during = 0
        for _ in range(5000):
            before = eng.stats.decode_steps
            eng.step()
            if req.state is RequestState.RESTORING:
                parked = True
            if eng._restoring:
                decode_during += eng.stats.decode_steps - before
            if req.state is RequestState.FINISHED:
                break
        assert req.state is RequestState.FINISHED
        assert parked, "disk restores must park, never run inline"
        assert decode_during > 0, "decode blocked on a disk restore"
        if bg.state is not RequestState.FINISHED:
            eng.cancel(bg.rid)
        eng.kv_transfer.close()

    def test_prefetch_hint_restores_from_disk_ahead_of_request(
        self, tiny, tmp_path
    ):
        cfg, params = tiny
        prompts = self._prompts(cfg, 1, 64, seed=8)
        samp = SamplingParams(temperature=0.0, max_new_tokens=2)
        eng = make_engine(tiny, tmp_path / "hint")
        spill_everything(eng, prompts, samp)
        eng.tree._evict_host(1 << 20)
        assert eng.tree.match_prefix(prompts[0]).disk_length > 0
        eng.kv_transfer.note_hint(prompts[0])
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            eng.step()
            if eng.tree.match_prefix(prompts[0]).length > 0:
                break
            eng.kv_transfer.wait_progress(0.01)
        m = eng.tree.match_prefix(prompts[0])
        assert m.length > 0, "hint never promoted the disk prefix"
        # The disk copy is retained: re-demotion stays free.
        node = m.last_node
        assert node.disk_value is not None
        eng.kv_transfer.close()

    def test_drain_flush_disk_commits_everything(self, tiny, tmp_path):
        cfg, params = tiny
        d = tmp_path / "drain"
        prompts = self._prompts(cfg, 2, 64, seed=9)
        samp = SamplingParams(temperature=0.0, max_new_tokens=2)
        eng = make_engine(tiny, d)
        for p in prompts:
            eng.generate([list(p)], samp)
        eng.drain_flush_hot()
        eng.kv_transfer.wait_host_ready()
        spilled, committed = eng.drain_flush_disk()
        assert spilled > 0 and committed is True
        assert eng._kv_tier.extents >= 2
        eng.kv_transfer.close()
        del eng
        eng2 = make_engine(tiny, d)
        assert eng2.resurrected["grafted_nodes"] >= 2
        eng2.kv_transfer.close()

    def test_cold_restart_resume_byte_identical(self, tiny, tmp_path):
        """The PR 7 seeded-replay contract composed with the tier: a
        stream interrupted by a whole-cell kill resumes byte-identical
        on a cell rebuilt from the extent directory alone."""
        cfg, params = tiny
        d = tmp_path / "resume"
        rng = np.random.default_rng(11)
        prompt = list(
            rng.integers(1, cfg.vocab_size - 1, size=96).astype(np.int32)
        )
        samp = SamplingParams(
            temperature=0.9, top_p=0.95, seed=4242, max_new_tokens=8
        )
        # Deterministic expectation on a pristine engine.
        ref = make_engine(tiny, tmp_path / "ref")
        r = ref.add_request(prompt, samp)
        while ref.has_work():
            ref.step()
        expected = list(r.generated)
        ref.kv_transfer.close()

        eng = make_engine(tiny, d)
        spill_everything(eng, [np.asarray(prompt)], samp)
        req = eng.add_request(prompt, samp)
        while len(req.generated) < 3:
            eng.step()
        delivered = list(req.generated)
        eng.kv_transfer.close()  # mid-decode whole-cell kill
        del eng

        eng2 = make_engine(tiny, d)
        c0 = eng2.stats.cached_tokens
        resumed = eng2.add_request(prompt, samp, resume_tokens=delivered)
        while eng2.has_work():
            eng2.step()
        assert delivered + list(resumed.generated) == expected
        assert eng2.stats.cached_tokens - c0 > 0  # replay hit disk KV
        eng2.kv_transfer.close()


# ---------------------------------------------------------------------------
# doctor: tier_thrash (live seam + postmortem) — satellite 3's tests
# ---------------------------------------------------------------------------


@pytest.mark.quick
class TestTierThrashRule:
    def _doctor_with_moves(self, moves, now=1000.0):
        from radixmesh_tpu.obs.doctor import MeshDoctor

        class FakeTier:
            recent_moves = moves

        class FakeEng:
            _kv_tier = FakeTier()
            _restoring = ()
            kv_transfer = None

            def telemetry(self):
                return {}

            def spec_report(self):
                return {}

        return MeshDoctor(engine=FakeEng(), now=lambda: now)

    def test_fires_on_sustained_flapping(self):
        moves = []
        for i in range(4):
            moves.append((990.0 + i, 7, "demote"))
            moves.append((990.5 + i, 7, "promote"))
        report = self._doctor_with_moves(moves).diagnose()
        (f,) = [x for x in report["findings"] if x["rule"] == "tier_thrash"]
        assert f["evidence"]["shard"] == 7
        assert f["evidence"]["cycles"] >= 3
        assert f["evidence"]["source"] == "live"

    def test_quiet_below_cycle_floor_and_outside_window(self):
        moves = [
            (990.0, 7, "demote"), (990.5, 7, "promote"),  # one cycle
            (100.0, 9, "demote"), (100.5, 9, "promote"),  # ancient
            (101.0, 9, "demote"), (101.5, 9, "promote"),
            (102.0, 9, "demote"), (102.5, 9, "promote"),
        ]
        report = self._doctor_with_moves(moves).diagnose()
        assert not [
            x for x in report["findings"] if x["rule"] == "tier_thrash"
        ]
        assert "tier_thrash" in report["rules_checked"]

    def test_one_way_demotion_is_not_thrash(self):
        moves = [(990.0 + i, 7, "demote") for i in range(10)]
        report = self._doctor_with_moves(moves).diagnose()
        assert not [
            x for x in report["findings"] if x["rule"] == "tier_thrash"
        ]

    def test_postmortem_variant_from_recorded_counters(self):
        from radixmesh_tpu.obs.doctor import postmortem_report

        pts_d, pts_p = [], []
        for i in range(4):
            pts_d.append([2 * i, 10.0 + i, float(i + 1)])
            pts_p.append([2 * i + 1, 10.5 + i, float(i + 1)])
        dump = {
            "node": "n0",
            "unclean": False,
            "interval_s": 1.0,
            "series": {
                'radixmesh_kv_tier_moves_total{dir="demote",shard="5",tier="e"}': pts_d,
                'radixmesh_kv_tier_moves_total{dir="promote",shard="5",tier="e"}': pts_p,
            },
            "last_t": 14.0,
            "last_seq": 7,
        }
        report = postmortem_report(dump)
        (f,) = [x for x in report["findings"] if x["rule"] == "tier_thrash"]
        assert f["evidence"]["shard"] == 5
        assert f["evidence"]["cycles"] >= 3
        assert "tier_thrash" in report["rules_checked"]

    def test_evidence_fields_pinned(self):
        from radixmesh_tpu.obs.doctor import (
            POSTMORTEM_EVIDENCE_FIELDS,
            RULE_EVIDENCE_FIELDS,
            RULES,
            POSTMORTEM_RULES,
        )

        assert "tier_thrash" in RULES
        assert "tier_thrash" in POSTMORTEM_RULES
        assert "shard" in RULE_EVIDENCE_FIELDS["tier_thrash"]
        assert "cycles" in POSTMORTEM_EVIDENCE_FIELDS["tier_thrash"]


# ---------------------------------------------------------------------------
# live acceptance: the TIER artifact's data source end to end
# ---------------------------------------------------------------------------


class TestTierWorkloadAcceptance:
    def test_run_tier_workload_gates_green(self):
        """One reduced-size live run of the whole acceptance workload:
        every validate_tier gate must hold on fresh data, not just on
        the checked-in artifact."""
        import bench
        from radixmesh_tpu.workload import run_tier_workload

        # Spills stage THROUGH the host arena, so one prefix must fit
        # it (prefix_tokens < host_slots) while the whole set exceeds
        # it 10x.
        res = run_tier_workload(
            n_prefixes=14, prefix_tokens=192, host_slots=256,
            n_streams=3, seed=1,
        )
        assert res["capacity"]["working_set_ratio"] >= 10
        assert (
            res["capacity"]["tier_hit_rate"]
            > res["capacity"]["baseline_hit_rate"]
        )
        assert res["restore_overlap"]["overlap_ok"]
        cs = res["cold_start"]
        assert cs["failed"] == 0
        assert cs["resumed"] == cs["interrupted"] > 0
        assert cs["byte_identical"] is True
        assert cs["disk_hit_tokens"] > 0
        assert cs["corrupt_detected"] >= 2 and cs["corrupt_served"] == 0
        report = bench.build_tier_report(
            res, meshcheck={"files": [], "findings": 0, "clean": True}
        )
        assert bench.validate_tier(report) == []


# ---------------------------------------------------------------------------
# review-hardening regressions (PR 15 code review)
# ---------------------------------------------------------------------------


@pytest.mark.quick
class TestReviewHardening:
    def test_stale_retired_ref_never_deletes_live_extent(self, tmp_path):
        """A retired ref whose path was since RE-committed (boundary-
        changed re-spill maps a NEW ref at the same name) must not
        delete the live extent or skew the books — _unlink is identity-
        guarded, not path-keyed."""
        tier = DiskKVTier(str(tmp_path / "t"), page_size=PAGE, name="stale")
        rng = np.random.default_rng(0)
        prefix = rng.integers(1, 100, size=8).astype(np.int32)
        seg = rng.integers(1, 100, size=8).astype(np.int32)
        kv = rng.standard_normal((2, 2, 8, 2, 4)).astype(np.float32)
        ref1 = tier.write_extent(prefix, seg, kv, None)
        tier.retire(ref1)  # the node split: old ref queued for unlink
        ref2 = tier.write_extent(prefix, seg, kv * 2, None)  # re-spill
        assert ref2.path == ref1.path
        tier.drain_retired()  # must be a no-op for the stale ref
        assert tier.has(ref2)
        assert os.path.exists(ref2.path)
        got, _ = tier.read_extent(ref2)
        np.testing.assert_array_equal(got, kv * 2)
        assert tier.resident_bytes == ref2.nbytes

    def test_transient_restore_failure_keeps_extent_attached(
        self, tiny, tmp_path
    ):
        """A restore unit that fails for a TRANSIENT reason (the extent
        file is intact) must leave node.disk_value attached for the
        next attempt; only a verification failure (file dropped by the
        tier) clears the ref."""
        from radixmesh_tpu.cache.kv_transfer import _RestoreUnit

        cfg, params = tiny
        rng = np.random.default_rng(1)
        p = rng.integers(1, cfg.vocab_size - 1, size=64).astype(np.int32)
        samp = SamplingParams(temperature=0.0, max_new_tokens=2)
        eng = make_engine(tiny, tmp_path / "transient")
        spill_everything(eng, [p], samp)
        eng.tree._evict_host(1 << 20)
        m = eng.tree.match_prefix(p)
        node = m.disk_nodes[0]
        ref = node.disk_value
        plane = eng.kv_transfer

        def failed_unit():
            dev = eng.pool.alloc(len(ref))
            u = _RestoreUnit(
                node, np.empty(0, dtype=np.int32), dev[: len(ref)],
                extent=ref, n_tokens=len(ref), failed=True,
            )
            return u

        # Transient: the tier still holds the extent -> ref retained.
        plane._apply_unit(eng.tree, failed_unit())
        assert node.disk_value is ref
        assert eng._kv_tier.has(ref)
        # Verification failure: the tier dropped the file -> ref cleared.
        eng._kv_tier._unlink(ref)
        plane._apply_unit(eng.tree, failed_unit())
        assert node.disk_value is None
        eng.kv_transfer.close()

    def test_advertised_value_never_pool_freed(self):
        """The resurrection re-announce publishes placeholder indices:
        _free_local must never release them (they alias live pool
        slots), while a normal same-rank PrefillValue still frees."""
        from radixmesh_tpu.cache.kv_pool import PagedKVPool
        from radixmesh_tpu.cache.mesh_cache import MeshCache
        from radixmesh_tpu.cache.mesh_values import (
            AdvertisedValue,
            PrefillValue,
        )
        from radixmesh_tpu.config import MeshConfig

        pool = PagedKVPool(
            num_slots=32, num_layers=1, num_kv_heads=1, head_dim=2,
            page_size=1,
        )
        mesh = MeshCache(
            MeshConfig(
                prefill_nodes=["a0"], decode_nodes=[], router_nodes=[],
                local_addr="a0", protocol="inproc",
            ),
            pool=pool,
        )
        taken = pool.alloc(8)
        free0 = pool.free_slots
        mesh._free_local(AdvertisedValue(taken, mesh.rank))
        assert pool.free_slots == free0  # advertisement: not freed
        mesh._free_local(PrefillValue(taken, mesh.rank))
        assert pool.free_slots == free0 + 8  # real publish: freed
        mesh.close()

    def test_postmortem_counter_baseline_not_an_event_burst(self):
        """A late-started/pruned history ring's first retained counter
        point carries the cumulative pre-window total — it is the
        BASELINE, not hundreds of moves at one instant, so a flat
        series must not fire tier_thrash."""
        from radixmesh_tpu.obs.doctor import postmortem_report

        dump = {
            "node": "n0", "unclean": False, "interval_s": 1.0,
            "series": {
                'radixmesh_kv_tier_moves_total{dir="demote",shard="3",tier="e"}':
                    [[0, 10.0, 500.0]],
                'radixmesh_kv_tier_moves_total{dir="promote",shard="3",tier="e"}':
                    [[1, 10.0, 500.0]],
            },
            "last_t": 10.0, "last_seq": 1,
        }
        report = postmortem_report(dump)
        assert not [
            f for f in report["findings"] if f["rule"] == "tier_thrash"
        ]


@pytest.mark.quick
class TestReviewHardeningRound2:
    def _mesh(self):
        from radixmesh_tpu.cache.kv_pool import PagedKVPool
        from radixmesh_tpu.cache.mesh_cache import MeshCache
        from radixmesh_tpu.config import MeshConfig

        pool = PagedKVPool(
            num_slots=32, num_layers=1, num_kv_heads=1, head_dim=2,
            page_size=1,
        )
        return MeshCache(
            MeshConfig(
                prefill_nodes=["a0"], decode_nodes=[], router_nodes=[],
                local_addr="a0", protocol="inproc",
            ),
            pool=pool,
        ), pool

    def test_advertised_value_never_enters_dup_ledger(self):
        """Conflict resolution recording an AdvertisedValue loser must
        not claim its placeholder ids (they alias live pool slots; a
        later _pending_free would free them under real data)."""
        from radixmesh_tpu.cache.mesh_values import AdvertisedValue
        from radixmesh_tpu.cache.mesh_cache import NodeKey

        mesh, pool = self._mesh()
        live = pool.alloc(8)  # live KV occupying slots 0..7
        adv = AdvertisedValue(np.arange(8, dtype=np.int32), mesh.rank)
        mesh._claim(NodeKey(np.arange(8, dtype=np.int32), mesh.rank), adv)
        assert not mesh._dup_pending
        mesh.close()

    def test_real_publish_upgrades_the_advertisement(self):
        """The origin's true publish after resurrection must REPLACE the
        placeholder value in the mesh tree (asymmetric eq + the upgrade
        branch in _resolve_conflict) — local_prefix_indices then maps
        to real slots, not arange ids; and a late advertisement never
        displaces real KV."""
        from radixmesh_tpu.cache.mesh_values import (
            AdvertisedValue,
            PrefillValue,
        )

        mesh, pool = self._mesh()
        key = np.arange(10, 18, dtype=np.int32)
        mesh.insert(key, np.arange(8, dtype=np.int32), advertise=True)
        node = mesh.tree.root.children[10]
        assert isinstance(node.value, AdvertisedValue)
        real = pool.alloc(8)
        conflicts0 = mesh._m_conflicts.value
        mesh.insert(key, real)  # the post-restore real publish
        node = mesh.tree.root.children[10]
        assert type(node.value) is PrefillValue
        np.testing.assert_array_equal(node.value.indices, real[:8])
        assert mesh._m_conflicts.value == conflicts0  # upgrade, not conflict
        assert not mesh._dup_pending
        # Reverse direction: a late advertisement must not displace it.
        mesh.insert(key, np.arange(8, dtype=np.int32), advertise=True)
        node = mesh.tree.root.children[10]
        assert type(node.value) is PrefillValue
        mesh.close()

    def test_poison_retired_when_spill_drop_frees_slots(self, tiny, tmp_path):
        """The spill 'poisoned' commit path frees arena slots — their
        poison entries must retire with them, or the next tenant's
        valid host copy gets condemned."""
        eng = make_engine(tiny, tmp_path / "poison")
        plane = eng.kv_transfer
        rng = np.random.default_rng(2)
        p = rng.integers(1, 100, size=64).astype(np.int32)
        samp = SamplingParams(temperature=0.0, max_new_tokens=2)
        eng.generate([list(p)], samp)
        eng.tree.evict(1 << 20)
        plane.wait_host_ready()
        m = eng.tree.match_prefix(p)
        node = m.host_nodes[0]
        slots = np.asarray(node.host_value, dtype=np.int32)
        with plane._lock:
            plane._poisoned_host.update(int(s) for s in slots)
        with plane._lock:
            plane._spilled.append((node, slots.copy(), None, "poisoned"))
        plane.pump(eng.tree)
        assert node.host_value is None  # garbage copy dropped
        with plane._lock:
            assert not (
                plane._poisoned_host & {int(s) for s in slots}
            ), "freed slots left poisoned: the next tenant would be condemned"
        plane.close()

    def test_capacity_purge_single_snapshot(self, tmp_path):
        """A deep purge sheds everything over budget in one pass and
        keeps the books exact (the O(extents^2) stat loop rewrite)."""
        tier = DiskKVTier(
            str(tmp_path / "t"), page_size=PAGE, name="purge",
            capacity_bytes=1,
        )
        rng = np.random.default_rng(3)
        for i in range(6):
            prefix = rng.integers(1, 100, size=4).astype(np.int32)
            seg = rng.integers(1, 100, size=8).astype(np.int32)
            kv = rng.standard_normal((2, 2, 8, 2, 4)).astype(np.float32)
            tier.write_extent(prefix, seg, kv, None)
        assert tier.extents == 1  # only the protected newest survives
        assert tier.resident_bytes > 0
        drops = sum(1 for m in tier.recent_moves if m[2] == "drop")
        assert drops == 5
