"""Seeded hot-path blocking: the sleep is two frames down from
``Engine.step`` — the call graph sees it, no module-scoped grep would
(``_drain_slow`` lives behind an innocent-looking helper)."""

import time


class Engine:
    def step(self):
        self._admit()

    def _admit(self):
        self._drain_slow()

    def _drain_slow(self):
        time.sleep(0.25)  # seeded: hotpath-blocking
