"""Seeded lock-order deadlock: A→B directly, B→A through a helper.

The B→A edge is invisible to any grep — ``report`` never mentions
``_a`` — but the acquisition graph sees ``_flush`` acquire it while
``report`` holds ``_b``.
"""

import threading


class Engine:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.stats = {}

    def step(self):
        with self._a:
            with self._b:  # seeded: lock-order-cycle
                self.stats["steps"] = self.stats.get("steps", 0) + 1

    def report(self):
        with self._b:
            return self._flush()

    def _flush(self):
        with self._a:
            return dict(self.stats)
