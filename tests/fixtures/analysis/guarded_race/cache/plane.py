"""Seeded guarded-by races.

``Plane``: the off-lock write sits TWO helper frames below its thread
root (``_scan_loop`` → ``_note`` → ``_retire``) — no grep scoped to any
one function can see that ``_retire``'s pop runs without the lock the
class's other two write sites hold.

``SplitLocks``: a write reachable from two thread roots with NO common
lock — each loop is locally "locked", but against different locks, so
the majority guard (``_la``) is absent on the ``_b_loop`` side.
"""

import threading


class Plane:
    def __init__(self):
        self._lock = threading.Lock()
        self._pending = {}
        self._t1 = threading.Thread(target=self._scan_loop, daemon=True)
        self._t2 = threading.Thread(target=self._apply_loop, daemon=True)

    def _scan_loop(self):
        while True:
            with self._lock:
                self._pending["scan"] = 1
            self._note()

    def _note(self):
        self._retire()

    def _retire(self):
        self._pending.pop("scan", None)  # seeded: guarded-by-race

    def _apply_loop(self):
        while True:
            with self._lock:
                if "scan" in self._pending:
                    self._pending["scan"] = 2


class SplitLocks:
    def __init__(self):
        self._la = threading.Lock()
        self._lb = threading.Lock()
        self.heat = {}
        self._ta = threading.Thread(target=self._a_loop, daemon=True)
        self._tb = threading.Thread(target=self._b_loop, daemon=True)

    def _a_loop(self):
        with self._la:
            self.heat["a"] = 1

    def rollup(self):
        with self._la:
            self.heat["rollup"] = sum(self.heat.values())

    def _b_loop(self):
        with self._lb:
            self.heat["b"] = 1  # seeded: guarded-by-race
