"""Seeded hot-path file I/O: the extent read is two frames down from
``Engine.step`` — a refactor dragging disk-tier I/O into the serving
loop would look exactly like this, and only the call graph sees it
(``_load_extent`` hides behind an innocent-looking helper). A second
seed proves the ``os.fsync`` shape trips too."""

import os


class Engine:
    def step(self):
        self._admit()

    def _admit(self):
        self._load_extent()

    def _load_extent(self):
        with open("/tmp/extent.kv", "rb") as fh:  # seeded: hotpath-file-io
            data = fh.read()
        return data

    def enqueue(self, req):
        self._commit(req)

    def _commit(self, req):
        fd = os.open("/tmp/extent.kv", os.O_WRONLY)
        os.fsync(fd)  # seeded: hotpath-file-io
        return req
