"""Seeded unhandled-state dispatch: the if/elif chain tests two of the
four declared RequestStates with no else — RESTORING and FINISHED fall
through silently (the wire_kinds fall-through shape, on a state
machine)."""

from .request import RequestState


class Engine:
    def poll(self, req):
        if req.state is RequestState.QUEUED:  # seeded: protocol-unhandled-state
            return "wait"
        elif req.state is RequestState.RUNNING:
            return "go"
