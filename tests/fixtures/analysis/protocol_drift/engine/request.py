"""The declared request state machine the sibling dispatch drifts from."""

import enum


class RequestState(enum.Enum):
    QUEUED = "queued"
    RESTORING = "restoring"
    RUNNING = "running"
    FINISHED = "finished"


VALID_TRANSITIONS = {
    (RequestState.QUEUED, RequestState.RUNNING),
    (RequestState.QUEUED, RequestState.RESTORING),
    (RequestState.RESTORING, RequestState.QUEUED),
    (RequestState.RUNNING, RequestState.FINISHED),
}
