"""Seeded lifecycle protocol drift: a state with no exit edge, and an
undeclared LEFT→ACTIVE "revival" transition whose source state is
statically known from the enclosing compare (nothing un-leaves)."""

import enum


class LifecycleState(enum.Enum):
    BOOTSTRAPPING = "bootstrapping"
    ACTIVE = "active"
    DRAINING = "draining"
    ZOMBIE = "zombie"  # seeded: protocol-no-exit
    LEFT = "left"


_VALID_TRANSITIONS = {
    (LifecycleState.BOOTSTRAPPING, LifecycleState.ACTIVE),
    (LifecycleState.ACTIVE, LifecycleState.DRAINING),
    (LifecycleState.ACTIVE, LifecycleState.ZOMBIE),
    (LifecycleState.DRAINING, LifecycleState.LEFT),
}


class LifecyclePlane:
    def __init__(self):
        self._state = LifecycleState.ACTIVE

    def _transition(self, new):
        if (self._state, new) not in _VALID_TRANSITIONS:
            raise RuntimeError("illegal")
        self._state = new

    def revive(self):
        if self._state is LifecycleState.LEFT:
            self._state = LifecycleState.ACTIVE  # seeded: protocol-undeclared-transition
