"""Seeded send-seam breaches: a raw blocking ``.send(`` and a
``try_send`` outside the documented seam methods."""


class MeshCache:
    def __init__(self, comm):
        self._comm = comm

    def publish(self, data):
        self._comm.send(data)  # seeded: send-seam

    def sneak_frame(self, data):
        return self._comm.try_send(data, timeout=0.1)  # seeded: send-seam

    def _sender_loop(self, data):
        return self._comm.try_send(data, timeout=0.1)  # allowed seam
