"""Seeded thread-map escapes: a lambda target no call graph can enter
(every frame it runs is invisible to the concurrency plane), and a
spawn without ``daemon=True`` that would wedge interpreter shutdown."""

import threading


class Workers:
    def start(self):
        t = threading.Thread(target=lambda: None, daemon=True)  # seeded: thread-target-unresolved
        u = threading.Thread(target=self._run)  # seeded: thread-daemonless
        return t, u

    def _run(self):
        pass
