"""Seeded metric-vocabulary breaches: an unprefixed family, a counter
without ``_total``, and a computed family name."""


def register(reg, name_suffix):
    hits = reg.counter("cache_hits_total", "prefix hits")  # seeded: metrics-prefix
    evictions = reg.counter("radixmesh_evictions", "evictions")  # seeded: metrics-unit
    dyn = reg.gauge("radixmesh_" + name_suffix, "computed")  # seeded: metrics-literal
    return hits, evictions, dyn
