"""Seeded metric-vocabulary breaches: an unprefixed family, a counter
without ``_total``, a computed family name, and a family registered but
never emitted (the dead-series drift that hid the PR 9 heat-gauge
clearing bug)."""


def register(reg, name_suffix):
    hits = reg.counter("cache_hits_total", "prefix hits")  # seeded: metrics-prefix
    evictions = reg.counter("radixmesh_evictions", "evictions")  # seeded: metrics-unit
    dyn = reg.gauge("radixmesh_" + name_suffix, "computed")  # seeded: metrics-literal
    hits.inc()
    evictions.inc()
    return hits, evictions, dyn


def register_ghost(reg):
    reg.counter("radixmesh_ghost_requests_total", "never emitted")  # seeded: metrics-dead
    live = reg.gauge("radixmesh_live_rows", "emitted below")
    live.set(1.0)
