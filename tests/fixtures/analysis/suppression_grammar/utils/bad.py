"""Seeded suppression-grammar breach: an ``ok[...]`` with no
justification must be a finding itself, never a silent excuse."""

import time


def lazy_backoff():
    # meshcheck: ok[sleep-audit]
    time.sleep(0.5)  # seeded: sleep-audit


def unjustified():
    # The directive above lazy_backoff is missing its justification, so
    # it both fails the grammar AND suppresses nothing.
    return 7


# seeded-at: utils/bad.py:8 suppression-grammar
