"""Seeded ownership-map second writer: a private construction plus an
owner-set poke — split-brain on the delivery plane."""

from radixmesh_tpu.cache.sharding import OwnershipMap


def build_private_map(view):
    m = OwnershipMap(epoch=1, rf=2, ranks=(0, 1), owners=())  # seeded: single-writer-ownership
    return m


def steal_shard(m, sid, rank):
    m.owners[sid] = (rank,)  # seeded: single-writer-ownership
