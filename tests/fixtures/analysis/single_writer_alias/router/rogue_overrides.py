"""Seeded override-map second writer (cache/rebalance.py's contract):
a private ShardOverrides construction plus a move-set poke — a second
decision-maker forking the owner sets every node derives from."""

from radixmesh_tpu.cache.rebalance import ShardOverrides


def fork_the_map():
    ovr = ShardOverrides(epoch=1, version=9, moves={3: (0, 1)})  # seeded: single-writer-overrides
    return ovr


def steal_move(ovr, sid, ranks):
    ovr.moves[sid] = tuple(ranks)  # seeded: single-writer-overrides
