"""Seeded single-writer violations, all through aliases — the shape the
old regex lints could not see (no ``= LifecycleState.`` / ``.owners =``
textual signature on the write line itself... except the binding, which
is the point: the AST checker flags both ends of the alias)."""

from radixmesh_tpu.policy.lifecycle import LifecycleState


def undrain(plane):
    st = LifecycleState.ACTIVE  # seeded: single-writer-lifecycle
    plane.state = st  # seeded: single-writer-lifecycle


def second_heat_counter(heat, sid):
    note = heat.note_insert  # seeded: single-writer-heat
    note(sid, 16)
