"""Seeded wire-kind breach: ``SHINY_NEW`` post-dates the pass-through
tolerance (declared after PREFETCH) but was never registered in
EXTENSION_KINDS — an old wire would raise on it instead of
forwarding."""

import enum


class OplogType(enum.IntEnum):
    INSERT = 1
    DELETE = 2
    RESET = 3
    PREFETCH = 11
    SHINY_NEW = 12  # seeded: wire-unregistered


EXTENSION_KINDS = frozenset({OplogType.PREFETCH})
DATA_KINDS = frozenset({OplogType.INSERT, OplogType.DELETE, OplogType.RESET})


class Oplog:
    def __init__(self, op_type, key=None):
        self.op_type = op_type
        self.key = key
