"""Companion mesh for the wire fixture: the REGISTERED vocabulary has
its encode sites and receive branches, so the only finding is the
seeded unregistered kind."""

from .oplog import Oplog, OplogType


class MeshCache:
    def insert(self, key):
        self._emit(Oplog(OplogType.INSERT, key))

    def delete(self, key):
        self._emit(Oplog(OplogType.DELETE, key))

    def reset_all(self):
        self._emit(Oplog(OplogType.RESET))

    def prefetch(self, key):
        self._emit(Oplog(OplogType.PREFETCH, key))

    def _emit(self, op):
        pass

    def oplog_received(self, op):
        if op.op_type is OplogType.PREFETCH:
            return
        if op.op_type in (OplogType.INSERT, OplogType.DELETE):
            return
        if op.op_type is OplogType.RESET:
            return
