"""6-process distributed correctness over real TCP (native C++ transport).

The reference's multi-node-without-a-cluster pattern (``correctness.py:22-29``:
3 prefill + 2 decode + 1 router OS processes on localhost) — with two
harness fixes called out in SURVEY §4: worker assertion failures propagate
to the parent's exit status, and phases synchronize on barriers instead of
fixed sleeps (sleeps remain only as replication settles).
"""

import multiprocessing as mp
import os
import socket
import sys
import time

import pytest


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


def _wait_for(pred, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def _worker(local_addr, prefill, decode, router, barrier, errq):
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        import numpy as np

        from radixmesh_tpu.cache.mesh_cache import MeshCache
        from radixmesh_tpu.config import MeshConfig, NodeRole

        cfg = MeshConfig(
            prefill_nodes=prefill,
            decode_nodes=decode,
            router_nodes=router,
            local_addr=local_addr,
            protocol="tcp",
            tick_interval_s=0.2,
            gc_interval_s=60.0,
        )
        node = MeshCache(cfg).start()
        assert node.wait_ready(timeout=30), "startup tick barrier timed out"
        barrier.wait(timeout=30)

        # Phase 1: prefill rank 1 writes; everyone converges; router routes.
        if node.role is NodeRole.PREFILL and node.rank == 1:
            node.insert([1, 2, 3], np.array([10, 20, 30], dtype=np.int32))
        if node.role is NodeRole.ROUTER:
            assert _wait_for(
                lambda: node.match_prefix([1, 2, 3, 4]).prefill_rank == 1
            ), "router never learned the prefill writer"
        else:
            assert _wait_for(lambda: node.match_prefix([1, 2, 3]).length == 3), (
                f"rank {node.rank} never converged on phase-1 insert"
            )
            assert all(v.rank == 1 for v in node.match_prefix([1, 2, 3]).values)
        barrier.wait(timeout=30)

        # Phase 2: multi-writer conflict converges to the lowest rank.
        if node.role is NodeRole.PREFILL:
            node.insert(
                [5, 6, 7], np.array([100 + node.rank] * 3, dtype=np.int32)
            )
        if node.role is not NodeRole.ROUTER:
            assert _wait_for(
                lambda: node.match_prefix([5, 6, 7]).length == 3
                and all(v.rank == 0 for v in node.match_prefix([5, 6, 7]).values)
            ), f"rank {node.rank} did not converge to rank 0's value"
        else:
            assert _wait_for(
                lambda: node.match_prefix([5, 6, 7]).prefill_rank == 0
            ), "router did not attribute the conflicted key to rank 0"
        barrier.wait(timeout=30)

        # Phase 3: decode extension -> router reports both ranks.
        if node.role is NodeRole.DECODE and node.local_rank == 0:
            node.insert(
                [1, 2, 3, 4, 5, 6], np.array([60 + i for i in range(6)], dtype=np.int32)
            )
        if node.role is NodeRole.ROUTER:
            assert _wait_for(
                lambda: node.match_prefix([1, 2, 3, 4, 5, 6, 7]).decode_rank
                == len(prefill)
            ), "router never learned the decode writer"
            res = node.match_prefix([1, 2, 3, 4, 5, 6, 7])
            assert res.prefill_rank == 1
        barrier.wait(timeout=30)
        node.close()
    except Exception as e:  # noqa: BLE001 — forward every failure to the parent
        errq.put(f"{local_addr}: {type(e).__name__}: {e}")
        sys.exit(1)


def test_six_process_tcp_ring():
    ports = _free_ports(6)
    prefill = [f"127.0.0.1:{p}" for p in ports[:3]]
    decode = [f"127.0.0.1:{p}" for p in ports[3:5]]
    router = [f"127.0.0.1:{p}" for p in ports[5:]]
    ctx = mp.get_context("spawn")
    barrier = ctx.Barrier(6)
    errq = ctx.Queue()
    procs = [
        ctx.Process(
            target=_worker, args=(addr, prefill, decode, router, barrier, errq)
        )
        for addr in prefill + decode + router
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=110)
    errors = []
    while not errq.empty():
        errors.append(errq.get())
    for p in procs:
        if p.is_alive():
            p.terminate()
            errors.append("worker still alive at timeout")
    assert not errors, "\n".join(errors)
    assert all(p.exitcode == 0 for p in procs), [p.exitcode for p in procs]
