"""Oplog protocol + binary serializer tests (reference ``cache_oplog.py`` /
``serializer.py`` capabilities, with the GC-payload-drop quirk fixed)."""

import numpy as np
import pytest

from radixmesh_tpu.cache.oplog import (
    GCEntry,
    NodeKey,
    Oplog,
    OplogType,
    deserialize,
    patched_ttl,
    serialize,
)


def roundtrip(op):
    return deserialize(serialize(op))


class TestSerializer:
    def test_insert_roundtrip(self):
        op = Oplog(
            op_type=OplogType.INSERT,
            origin_rank=2,
            logic_id=12345678901,
            ttl=5,
            key=np.array([1, 2, 3], dtype=np.int32),
            value=np.array([100, 101, 102], dtype=np.int32),
            value_rank=2,
        )
        assert roundtrip(op) == op

    def test_tick_roundtrip_empty_payload(self):
        op = Oplog(op_type=OplogType.TICK, origin_rank=3, logic_id=7, ttl=10)
        got = roundtrip(op)
        assert got == op
        assert len(got.key) == 0 and len(got.value) == 0

    def test_gc_payload_survives_wire(self):
        # The reference drops gc fields in to_dict (cache_oplog.py:58-66);
        # here they must round-trip fully.
        op = Oplog(
            op_type=OplogType.GC_QUERY,
            origin_rank=1,
            logic_id=9,
            ttl=5,
            gc=[
                GCEntry(key=np.array([5, 6], dtype=np.int32), value_rank=4, agree=3),
                GCEntry(key=np.array([9], dtype=np.int32), value_rank=0, agree=1),
            ],
        )
        got = roundtrip(op)
        assert got == op
        assert got.gc[0].agree == 3 and got.gc[0].value_rank == 4
        np.testing.assert_array_equal(got.gc[1].key, [9])

    def test_gc_exec_roundtrip(self):
        op = Oplog(
            op_type=OplogType.GC_EXEC,
            origin_rank=0,
            logic_id=1,
            ttl=5,
            gc=[GCEntry(key=np.array([1, 2, 3], dtype=np.int32), value_rank=2)],
        )
        assert roundtrip(op) == op

    def test_bad_magic_rejected(self):
        with pytest.raises(ValueError, match="magic"):
            deserialize(b"\x00" * 64)

    def test_bad_version_rejected(self):
        buf = bytearray(serialize(Oplog(OplogType.TICK, 0, 0, 1)))
        buf[1] = 99
        with pytest.raises(ValueError, match="version"):
            deserialize(bytes(buf))

    def test_large_payload(self):
        key = np.arange(100_000, dtype=np.int32)
        op = Oplog(OplogType.INSERT, 0, 1, 5, key=key, value=key * 2, value_rank=0)
        got = roundtrip(op)
        np.testing.assert_array_equal(got.value, key * 2)


class TestNodeKey:
    def test_hash_and_eq(self):
        a = NodeKey([1, 2, 3], 0)
        b = NodeKey(np.array([1, 2, 3]), 0)
        c = NodeKey([1, 2, 3], 1)
        assert a == b and hash(a) == hash(b)
        assert a != c
        d = {a: "x"}
        assert d[b] == "x"
        assert c not in d


class TestWireVersionCompat:
    def test_v1_frames_still_accepted(self):
        """Rolling restart: a v2 node must apply frames from v1 peers
        (24-byte header, no ts) instead of dropping their replication."""
        import struct

        key = np.array([1, 2, 3], dtype=np.int32)
        value = np.array([10, 11, 12], dtype=np.int32)
        v1 = b"".join(
            [
                struct.pack("<BBBxiqii", 0x52, 1, int(OplogType.INSERT), 4, 9, 5, 4),
                struct.pack("<III", len(key), len(value), 0),
                key.tobytes(),
                value.tobytes(),
            ]
        )
        op = deserialize(v1)
        assert op.op_type is OplogType.INSERT
        assert op.origin_rank == 4
        assert op.logic_id == 9
        assert op.ttl == 5
        assert op.value_rank == 4
        assert op.ts == 0.0
        np.testing.assert_array_equal(op.key, key)
        np.testing.assert_array_equal(op.value, value)

    def test_emit_v1_round_trips_without_ts(self):
        """Rolling upgrade: RADIXMESH_WIRE_VERSION=1 makes upgraded nodes
        emit frames v1 peers can parse; ts is the only casualty."""
        from radixmesh_tpu.cache.oplog import set_emit_version

        op = Oplog(
            OplogType.INSERT, 2, 3, 4,
            key=np.array([1, 2], dtype=np.int32),
            value=np.array([7, 8], dtype=np.int32),
            value_rank=2, ts=99.0,
        )
        set_emit_version(1)
        try:
            buf = serialize(op)
            assert buf[1] == 1  # version byte
            got = deserialize(buf)
        finally:
            set_emit_version(3)
        assert got.ts == 0.0
        assert got.origin_rank == 2 and got.value_rank == 2 and got.ttl == 4
        np.testing.assert_array_equal(got.key, op.key)
        np.testing.assert_array_equal(got.value, op.value)

    def test_v2_frames_still_accepted_page_defaults_1(self):
        """A v2 frame (pre-page header) parses with page=1."""
        import struct

        key = np.array([5, 6], dtype=np.int32)
        value = np.array([20, 21], dtype=np.int32)
        v2 = b"".join(
            [
                struct.pack(
                    "<BBBxiqiid", 0x52, 2, int(OplogType.INSERT),
                    1, 7, 3, 1, 12.5,
                ),
                struct.pack("<III", len(key), len(value), 0),
                key.tobytes(),
                value.tobytes(),
            ]
        )
        op = deserialize(v2)
        assert op.page == 1
        assert op.ts == 12.5
        np.testing.assert_array_equal(op.value, value)

    def test_v3_page_round_trips(self):
        op = Oplog(
            OplogType.INSERT, 1, 2, 3,
            key=np.arange(32, dtype=np.int32),
            value=np.array([4, 9], dtype=np.int32),  # two page ids
            value_rank=1, ts=5.0, page=16,
        )
        buf = serialize(op)
        assert buf[1] == 3
        got = deserialize(buf)
        assert got.page == 16
        assert got == op

    def test_page_granular_requires_v3_emit(self):
        """A rolling upgrade pinned to an older emit version cannot
        silently drop the page field — it must refuse."""
        from radixmesh_tpu.cache.oplog import set_emit_version

        op = Oplog(
            OplogType.INSERT, 0, 1, 2,
            key=np.arange(16, dtype=np.int32),
            value=np.array([0], dtype=np.int32),
            value_rank=0, page=16,
        )
        set_emit_version(2)
        try:
            with pytest.raises(ValueError, match="wire v3"):
                serialize(op)
        finally:
            set_emit_version(3)


class TestPatchedTtl:
    """Ring forwarding patches the TTL in the received frame instead of
    re-serializing the payload; the patch must be position-exact for both
    wire versions."""

    def test_patch_preserves_everything_but_ttl(self):
        from radixmesh_tpu.cache.oplog import patched_ttl

        op = Oplog(
            op_type=OplogType.INSERT, origin_rank=3, logic_id=77,
            ttl=5, value_rank=2, key=np.arange(9, dtype=np.int32),
            value=np.arange(9, dtype=np.int32) * 10, ts=123.5,
        )
        data = serialize(op)
        back = deserialize(patched_ttl(data, 4))
        assert back.ttl == 4
        expect = deserialize(data)
        expect.ttl = 4
        assert back == expect

    def test_patch_v1_frames(self):
        from radixmesh_tpu.cache.oplog import patched_ttl, set_emit_version

        set_emit_version(1)
        try:
            op = Oplog(
                op_type=OplogType.TICK, origin_rank=1, logic_id=5, ttl=8,
            )
            data = serialize(op)
        finally:
            set_emit_version(3)
        back = deserialize(patched_ttl(data, 7))
        assert back.ttl == 7
        assert back.origin_rank == 1 and back.logic_id == 5


class TestU24Packing:
    """v3 packs key/value arrays 3 bytes per element when they fit 24
    bits (every real vocabulary and pool does); out-of-range arrays fall
    back to int32 per array, signalled by header flags."""

    def test_round_trip_and_size(self):
        op = Oplog(
            OplogType.INSERT, 0, 1, 5,
            key=np.arange(256, dtype=np.int32),
            value=np.arange(16, dtype=np.int32),
            value_rank=0, page=16,
        )
        buf = serialize(op)
        got = deserialize(buf)
        assert got == op
        from radixmesh_tpu.cache.oplog import _HEADER_V3

        assert len(buf) == _HEADER_V3.size + 12 + 3 * (256 + 16)

    def test_out_of_range_values_fall_back_to_int32(self):
        for bad in (np.array([1 << 24], np.int32), np.array([-5], np.int32)):
            op = Oplog(
                OplogType.INSERT, 0, 1, 5,
                key=bad, value=np.array([3], np.int32), value_rank=0,
            )
            got = deserialize(serialize(op))
            np.testing.assert_array_equal(got.key, bad)
            np.testing.assert_array_equal(got.value, [3])

    def test_boundary_values(self):
        key = np.array([0, (1 << 24) - 1, 12345], np.int32)
        op = Oplog(OplogType.INSERT, 0, 1, 5, key=key,
                   value=key.copy(), value_rank=0)
        got = deserialize(serialize(op))
        np.testing.assert_array_equal(got.key, key)
        np.testing.assert_array_equal(got.value, key)

    def test_mixed_flags(self):
        """Key fits u24, value does not: each array chooses its own
        encoding."""
        op = Oplog(
            OplogType.INSERT, 0, 1, 5,
            key=np.array([7, 8], np.int32),
            value=np.array([1 << 25, 4], np.int32),
            value_rank=0,
        )
        got = deserialize(serialize(op))
        assert got == op

    def test_patched_ttl_still_works(self):
        op = Oplog(OplogType.INSERT, 2, 9, 6,
                   key=np.arange(32, dtype=np.int32),
                   value=np.arange(2, dtype=np.int32), value_rank=2, page=16)
        from radixmesh_tpu.cache.oplog import patched_ttl

        back = deserialize(patched_ttl(serialize(op), 3))
        assert back.ttl == 3
        np.testing.assert_array_equal(back.key, op.key)


@pytest.mark.quick
class TestPrefetchOp:
    """PR 4: the PREFETCH hint kind rides the existing wire unchanged,
    and UNKNOWN kinds (a newer peer's extension) deserialize to their
    raw int instead of raising — the forward-compat contract that lets
    pre-PREFETCH nodes coexist with hint-senders."""

    def test_prefetch_round_trips(self):
        op = Oplog(
            op_type=OplogType.PREFETCH,
            origin_rank=3,
            logic_id=11,
            ttl=1,
            key=np.arange(32, dtype=np.int32),
            value_rank=0,
            ts=123.5,
        )
        back = deserialize(serialize(op))
        assert back == op
        assert back.op_type is OplogType.PREFETCH

    def test_unknown_kind_deserializes_to_raw_int(self):
        op = Oplog(
            op_type=OplogType.PREFETCH, origin_rank=1, logic_id=2, ttl=1,
            key=np.arange(4, dtype=np.int32),
        )
        frame = bytearray(serialize(op))
        frame[2] = 213  # a kind from the future
        back = deserialize(bytes(frame))
        assert back.op_type == 213
        assert not isinstance(back.op_type, OplogType)
        # ...and such frames can still be TTL-patched for forwarding.
        patched = deserialize(patched_ttl(bytes(frame), 0))
        assert patched.ttl == 0 and patched.op_type == 213


@pytest.mark.quick
class TestRepairOps:
    """PR 5: the anti-entropy REPAIR_PROBE/REPAIR_SUMMARY kinds ride the
    existing wire unchanged (value = packed payload, value_rank = the
    addressed peer) and are registered as extension kinds, so an old
    wire sees an unknown int and forwards instead of raising."""

    @pytest.mark.parametrize(
        "kind", [OplogType.REPAIR_PROBE, OplogType.REPAIR_SUMMARY]
    )
    def test_repair_round_trips(self, kind):
        op = Oplog(
            op_type=kind,
            origin_rank=2,
            logic_id=41,
            ttl=1,
            value=np.arange(132, dtype=np.int32),  # a packed bucket vector
            value_rank=0,
            ts=77.25,
        )
        back = deserialize(serialize(op))
        assert back == op
        assert back.op_type is kind

    def test_repair_kinds_are_extension_registered(self):
        from radixmesh_tpu.cache.oplog import EXTENSION_KINDS

        assert OplogType.REPAIR_PROBE in EXTENSION_KINDS
        assert OplogType.REPAIR_SUMMARY in EXTENSION_KINDS


@pytest.mark.quick
class TestTraceTrailer:
    """PR 9 cross-node stitching: data frames may carry an OPTIONAL
    8-byte trace-id trailer behind a v3 flags bit. The compat contract
    is the EXTENSION_KINDS one transposed to payload bytes: a pre-PR-9
    decoder parses exactly the offsets it knows and never inspects
    trailing bytes (raw pass-through — forwarding patches the original
    frame in place, so the trailer survives old hops untouched), and a
    PR-9 decoder reads traceless frames exactly as before."""

    def _op(self, trace_id=0):
        return Oplog(
            op_type=OplogType.INSERT,
            origin_rank=1,
            logic_id=99,
            ttl=4,
            key=np.arange(1, 9, dtype=np.int32),
            value=np.arange(8, dtype=np.int32),
            value_rank=1,
            ts=12.5,
            trace_id=trace_id,
        )

    @staticmethod
    def _legacy_v3_decode(buf: bytes):
        """A faithful PRE-PR-9 v3 parser (the header/array/GC layout
        verbatim, no knowledge of the trace flag or trailer) — the
        stand-in for an old peer's deserialize in the compat tests."""
        import struct

        mv = memoryview(buf)
        hdr = struct.Struct("<BBBxiqiidBBxx")
        (_, ver, op_type, origin, logic, ttl, value_rank, ts,
         page, flags) = hdr.unpack_from(mv, 0)
        assert ver == 3
        off = hdr.size
        key_len, val_len, n_gc = struct.unpack_from("<III", mv, off)
        off += 12

        def _arr(count, u24):
            nonlocal off
            if u24:
                raw = np.frombuffer(mv, np.uint8, 3 * count, off)
                out = np.zeros((count, 4), np.uint8)
                out[:, :3] = raw.reshape(count, 3)
                off += 3 * count
                return out.view(np.int32).reshape(count)
            a = np.frombuffer(mv, np.int32, count, off).copy()
            off += 4 * count
            return a

        key = _arr(key_len, flags & 1)
        value = _arr(val_len, flags & 2)
        assert n_gc == 0
        # A pre-PR-9 parser STOPS here: trailing bytes are never read.
        return dict(
            op_type=op_type, origin=origin, logic=logic, ttl=ttl,
            value_rank=value_rank, ts=ts, page=page,
            key=key, value=value, consumed=off,
        )

    def test_trace_id_round_trips(self):
        op = self._op(trace_id=0xFEED_FACE_CAFE_F00D)
        back = deserialize(serialize(op))
        assert back == op
        assert back.trace_id == 0xFEED_FACE_CAFE_F00D

    def test_traceless_frame_is_bit_for_bit_pre_trace(self):
        """trace_id=0 emits NO flag and NO trailer: stripping the traced
        frame's trailer and clearing its flag bit yields byte-identical
        output — i.e. tracing adds exactly (bit, 8 bytes) and tracing
        OFF costs zero wire change."""
        from radixmesh_tpu.cache import oplog as om

        plain = serialize(self._op())
        traced = serialize(self._op(trace_id=7))
        assert len(traced) == len(plain) + 8
        stripped = bytearray(traced[:-8])
        assert stripped[om._FLAGS_OFFSET] & om._FLAG_TRACE
        stripped[om._FLAGS_OFFSET] &= ~om._FLAG_TRACE
        assert bytes(stripped) == plain
        assert deserialize(plain).trace_id == 0

    def test_trace_bearing_frame_decodes_on_a_pre_pr9_peer(self):
        """The satellite compat gate: an OLD v3 parser reads every field
        of a trace-bearing frame correctly and simply never sees the
        trailer (its parse ends 8 bytes early — raw pass-through)."""
        op = self._op(trace_id=0xAB_CDEF_0123_4567)
        frame = serialize(op)
        legacy = self._legacy_v3_decode(frame)
        assert legacy["origin"] == op.origin_rank
        assert legacy["ttl"] == op.ttl
        assert legacy["ts"] == op.ts
        assert np.array_equal(legacy["key"], op.key)
        assert np.array_equal(legacy["value"], op.value)
        assert legacy["consumed"] == len(frame) - 8

    def test_patched_ttl_and_frame_preserve_the_trailer(self):
        """Ring forwarding patches the ORIGINAL bytes (TTL / scope /
        value_rank at fixed offsets), so the trailer must survive every
        hop untouched — including hops through pre-PR-9 peers, which
        use the same in-place patch."""
        from radixmesh_tpu.cache.oplog import patched_frame

        frame = serialize(self._op(trace_id=0x1234_5678_9ABC_DEF0))
        hopped = patched_ttl(frame, 1)
        assert deserialize(hopped).trace_id == 0x1234_5678_9ABC_DEF0
        assert deserialize(hopped).ttl == 1
        scoped = patched_frame(frame, ttl=2, spine=True, value_rank=5)
        back = deserialize(scoped)
        assert back.trace_id == 0x1234_5678_9ABC_DEF0
        assert back.spine and back.value_rank == 5

    def test_pre_v3_emit_drops_trace_silently(self):
        """A rolling upgrade pinned to wire v2 cannot carry the trailer:
        serialize drops the id (tracing degrades; the wire never
        breaks), unlike page/spine which hard-fail because they change
        APPLY semantics."""
        from radixmesh_tpu.cache.oplog import set_emit_version

        op = self._op(trace_id=42)
        op.page = 1
        try:
            set_emit_version(2)
            back = deserialize(serialize(op))
            assert back.trace_id == 0
        finally:
            set_emit_version(3)

    def test_truncated_trailer_degrades_to_untraced(self):
        """Flag set but trailer missing (a corrupt or truncated frame):
        decode as untraced rather than raise — stitching is telemetry,
        never worth a dropped frame."""
        frame = bytearray(serialize(self._op(trace_id=99)))
        del frame[-8:]  # trailer gone, flag still set
        assert deserialize(bytes(frame)).trace_id == 0
