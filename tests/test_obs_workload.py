"""Mesh-wide observability acceptance (PR 9, ``workload.run_obs_workload``):
the chaos-style crash+resurrection run must export ONE stitched Perfetto
trace with the interrupted request's spans on >= 3 node tracks under a
single 64-bit trace id (publish/replication edges visible), the zipf
workload must provably drive the per-shard skew score with the hot
shard's owner set correctly named from gossip alone, and traceless
frames must stay bit-for-bit the pre-PR-9 wire. (The step-attribution
leg is exercised separately in test_trace_plane — no second tiny-engine
compile here.)"""

import json

import pytest

import bench
from radixmesh_tpu.workload import run_obs_workload


class TestObsScenario:
    def test_stitch_heat_and_wire_gates(self, tmp_path):
        trace_path = str(tmp_path / "stitched.json")
        res = run_obs_workload(
            streams=6,
            tokens_per_stream=16,
            zipf_inserts=250,
            engine_steps=False,
            stitched_trace_path=trace_path,
            timeout_s=45.0,
        )
        report = bench.build_obs_report(res)
        # Gates (validate_obs enforces them too; asserted directly so a
        # failure names the exact leg). steps is gate-exempt here
        # (performed=False — covered by test_trace_plane's engine test).
        assert bench.validate_obs(report) == []
        stitch = res["stitch"]
        assert stitch["failed"] == 0
        assert stitch["interrupted"] > 0
        assert stitch["resumed"] == stitch["interrupted"]
        assert stitch["node_tracks"] >= bench.OBS_MIN_NODE_TRACKS
        assert stitch["replication_edges"] > 0
        assert stitch["publish_edges"] > 0
        heat = res["heat"]
        assert heat["skew_score"] >= bench.OBS_MIN_SKEW_SCORE
        assert heat["hot_shard"] == heat["expected_hot_shard"]
        assert heat["owner_set_correct"]
        wire = res["wire"]
        assert wire["rf0_traceless_unchanged"]
        assert wire["trace_trailer_roundtrip"]
        assert wire["trailer_bytes"] == 8

        # The stitched artifact is ONE valid Perfetto document with one
        # process track per node and the single trace id threaded
        # through the interrupted request's events.
        with open(trace_path) as fh:
            doc = json.load(fh)
        assert bench.validate_trace(doc) == []
        assert doc["otherData"]["stitched"] is True
        procs = {
            ev["args"]["name"]
            for ev in doc["traceEvents"]
            if ev.get("ph") == "M" and ev["name"] == "process_name"
        }
        assert set(stitch["nodes_on_track"]) <= procs
        tid = stitch["trace_id"]
        pids_under_tid = {
            ev["pid"]
            for ev in doc["traceEvents"]
            if ev.get("ph") == "X"
            and (ev.get("args") or {}).get("trace_id") == tid
        }
        assert len(pids_under_tid) >= bench.OBS_MIN_NODE_TRACKS

    @pytest.mark.quick
    def test_emitter_report_shape(self):
        """scripts/obsbench.py assembles through the same builder the
        schema tests pin — import seam only (the full run is the
        unmarked test above + the checked-in artifact)."""
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "obsbench",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                "scripts",
                "obsbench.py",
            ),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert callable(mod.run) and callable(mod.main)
