"""W8A16 weight quantization (ops/wquant.py): storage halves, logits stay
within per-out-channel int8 error, and the serving engine runs end-to-end
on quantized weights (VERDICT round-4 next-step #7 — the path that puts
Llama-3-8B on one 16 GB v5e)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from radixmesh_tpu.engine import Engine, SamplingParams
from radixmesh_tpu.models.llama import (
    ModelConfig,
    init_params,
    param_logical_axes,
    prefill_forward,
)
from radixmesh_tpu.ops.wquant import (
    LAYER_QUANT_WEIGHTS,
    quantize_params,
    quantize_weight,
)


@pytest.fixture(scope="module")
def model():
    cfg = ModelConfig.tiny().replace(dtype=jnp.float32)
    params = init_params(cfg, jax.random.PRNGKey(11))
    return cfg, params


class TestQuantizeWeight:
    def test_round_trip_error_bounded(self):
        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(size=(64, 96)), jnp.float32)
        q, s = quantize_weight(w, axis=0)
        assert q.dtype == jnp.int8
        assert s.shape == (96,)
        deq = np.asarray(q, np.float32) * np.asarray(s)[None, :]
        # Symmetric int8 per-channel: error ≤ scale/2 per element.
        err = np.abs(deq - np.asarray(w))
        assert np.all(err <= np.asarray(s)[None, :] * 0.5 + 1e-7)

    def test_outlier_channel_isolated(self):
        """One huge output channel must not inflate the others' scales."""
        w = np.ones((8, 4), np.float32)
        w[:, 2] = 1000.0
        q, s = quantize_weight(jnp.asarray(w), axis=0)
        s = np.asarray(s)
        assert s[2] > 5.0 and np.all(s[[0, 1, 3]] < 0.01)


class TestQuantizeParams:
    def test_leaves_and_scales(self, model):
        cfg, params = model
        qp = quantize_params(params)
        for name in LAYER_QUANT_WEIGHTS:
            w = qp["layers"][name]
            assert w.dtype == jnp.int8, name
            s = qp["layers"][name + "_s"]
            assert s.shape == w.shape[:1] + w.shape[2:], name
        assert qp["embed"].dtype == jnp.int8
        assert qp["embed_s"].shape == (cfg.vocab_size,)
        assert qp["lm_head"].dtype == jnp.int8
        assert qp["lm_head_s"].shape == (cfg.vocab_size,)
        # Norms stay full precision.
        assert qp["final_norm"].dtype == params["final_norm"].dtype
        # Idempotent.
        qp2 = quantize_params(qp)
        assert qp2["layers"]["wq"] is qp["layers"]["wq"]

    def test_axes_cover_scales(self, model):
        cfg, params = model
        qp = quantize_params(params)
        axes = param_logical_axes(cfg, qp)
        for name in LAYER_QUANT_WEIGHTS:
            assert name + "_s" in axes["layers"], name
        assert axes["lm_head_s"] == ("vocab",)
        flat_p = jax.tree.leaves(qp)
        flat_a = jax.tree.leaves(axes, is_leaf=lambda x: isinstance(x, tuple))
        assert len(flat_p) == len(flat_a)

    def test_logits_close_to_full_precision(self, model):
        cfg, params = model
        qp = quantize_params(params)
        rng = np.random.default_rng(5)
        tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 12)), jnp.int32)
        positions = jnp.broadcast_to(jnp.arange(12)[None], (2, 12))
        ck = jnp.zeros((cfg.n_layers, 2, 0, cfg.n_kv_heads, cfg.head_dim), cfg.dtype)
        want, _, _ = prefill_forward(
            params, cfg, tokens, positions, ck, ck, jnp.zeros((2,), jnp.int32)
        )
        got, _, _ = prefill_forward(
            qp, cfg, tokens, positions, ck, ck, jnp.zeros((2,), jnp.int32)
        )
        w, g = np.asarray(want), np.asarray(got)
        # Per-channel int8 weights: logits track within a small fraction
        # of the logit RANGE (quantization noise accumulates over layers).
        span = np.abs(w).max()
        assert np.abs(g - w).max() < 0.05 * span
        # Greedy decisions overwhelmingly agree.
        agree = (w.argmax(-1) == g.argmax(-1)).mean()
        assert agree >= 0.9


class TestEngineWeightQuant:
    def test_generate_runs_and_tracks_bf16(self, model):
        cfg, params = model
        prompts = [
            np.random.default_rng(7).integers(0, cfg.vocab_size, 10).tolist()
            for _ in range(2)
        ]
        eng = Engine(
            cfg, params, num_slots=256, page_size=4, max_batch=2,
            max_seq_len=64, weight_quant="int8",
        )
        out = eng.generate(prompts, SamplingParams(max_new_tokens=6))
        assert all(len(o) == 6 for o in out)
        assert all(0 <= t < cfg.vocab_size for o in out for t in o)

    def test_pp_matches_single_device(self, model):
        """W8A16 weights under pp x tp: the scale leaves shard with their
        weights (pp_layer_specs), the pipeline embeds/head-projects
        through the int8 table, and greedy tokens match the single-device
        int8-weight engine exactly."""
        cfg, params = model
        if len(jax.devices()) < 4:
            pytest.skip("needs >=4 devices for a pp x tp mesh")
        prompts = [
            np.random.default_rng(9).integers(1, cfg.vocab_size, 14).tolist(),
            np.random.default_rng(10).integers(1, cfg.vocab_size, 9).tolist(),
        ]
        sampling = SamplingParams(temperature=0.0, max_new_tokens=6)
        single = Engine(
            cfg, params, num_slots=512, page_size=4, max_batch=2,
            weight_quant="int8",
        )
        want = single.generate(prompts, sampling)
        mesh = jax.sharding.Mesh(
            np.asarray(jax.devices()[:4]).reshape(2, 2), ("pp", "tp")
        )
        pp_eng = Engine(
            cfg, params, num_slots=512, page_size=4, max_batch=2,
            weight_quant="int8", device_mesh=mesh,
            decode_steps_per_launch=3,
        )
        got = pp_eng.generate(prompts, sampling)
        assert got == want


class TestRandomW8Params:
    def test_decodes_through_engine(self, model):
        """Host-side W8A16 random init (the 8B-on-one-chip bench path)
        must produce a pytree the whole serving stack accepts."""
        from radixmesh_tpu.ops.wquant import random_w8_params

        cfg, _ = model
        params = random_w8_params(cfg, seed=3, dtype=cfg.dtype)
        assert params["layers"]["wq"].dtype == np.int8
        assert params["embed"].dtype == np.int8
        eng = Engine(
            cfg, jax.tree.map(jnp.asarray, params), num_slots=256,
            page_size=4, max_batch=2, max_seq_len=64,
        )
        prompts = [
            np.random.default_rng(4).integers(0, cfg.vocab_size, 9).tolist()
        ]
        out = eng.generate(prompts, SamplingParams(max_new_tokens=5))
        assert len(out[0]) == 5

    def test_matches_quantize_params_scheme(self, model):
        """Same quantization scheme as quantize_params: per-out-channel
        over the contraction axis, embed per row."""
        from radixmesh_tpu.ops.wquant import random_w8_params

        cfg, _ = model
        p = random_w8_params(cfg, seed=0, dtype=cfg.dtype)
        L = cfg.n_layers
        assert p["layers"]["wq_s"].shape == (L, cfg.n_heads * cfg.head_dim)
        assert p["layers"]["w_down_s"].shape == (L, cfg.hidden)
        assert p["embed_s"].shape == (cfg.vocab_size,)
        deq = p["layers"]["wq"][0].astype(np.float32) * p["layers"]["wq_s"][0]
        assert np.abs(deq).std() > 0  # non-degenerate init
        # int8 payload actually saturates the range somewhere.
        assert p["layers"]["wq"].max() == 127 or p["layers"]["wq"].min() == -127


class TestW8Compositions:
    def test_spec_decode_compose(self, model):
        """Speculative decoding over W8A16 weights: greedy output must be
        bit-identical to the non-speculative int8-weight engine (spec
        verify and plain decode share the same quantized forward)."""
        cfg, params = model
        prompt = np.random.default_rng(3).integers(
            0, cfg.vocab_size, 12
        ).tolist()
        # Force a repeated n-gram so prompt-lookup drafting has material.
        prompt = prompt + prompt[:6]
        sampling = SamplingParams(temperature=0.0, max_new_tokens=8)
        plain = Engine(
            cfg, params, num_slots=256, page_size=4, max_batch=1,
            max_seq_len=96, weight_quant="int8",
        )
        want = plain.generate([prompt], sampling)[0]
        spec = Engine(
            cfg, params, num_slots=256, page_size=4, max_batch=1,
            max_seq_len=96, weight_quant="int8", spec_decode_tokens=3,
        )
        got = spec.generate([prompt], sampling)[0]
        assert got == want

    def test_qwen2_bias_compose(self):
        """Qwen2's qkv biases stay full-precision and add AFTER the
        per-out-channel scale — logits must track the bf16 engine."""
        from radixmesh_tpu.models import get_config
        from radixmesh_tpu.models.llama import init_params

        cfg = get_config("qwen2-tiny", dtype=jnp.float32)
        assert cfg.qkv_bias
        params = init_params(cfg, jax.random.PRNGKey(4))
        # Give ALL the biases real values (zeros would hide an
        # add-before-scale ordering bug in any of the three projections).
        for i, name in enumerate(("bq", "bk", "bv")):
            params["layers"][name] = (
                jax.random.normal(jax.random.PRNGKey(5 + i),
                                  params["layers"][name].shape) * 0.1
            )
        prompt = np.random.default_rng(6).integers(
            0, cfg.vocab_size, 10
        ).tolist()
        sampling = SamplingParams(temperature=0.0, max_new_tokens=6)
        base = Engine(cfg, params, num_slots=256, page_size=4, max_batch=1,
                      max_seq_len=64)
        w8 = Engine(cfg, params, num_slots=256, page_size=4, max_batch=1,
                    max_seq_len=64, weight_quant="int8")
        out_base = base.generate([prompt], sampling)[0]
        out_w8 = w8.generate([prompt], sampling)[0]
        assert len(out_w8) == 6
        # Quantization may flip a rare argmax; prefixes overwhelmingly
        # agree on a tiny model.
        agree = sum(a == b for a, b in zip(out_base, out_w8))
        assert agree >= 4, (out_base, out_w8)
