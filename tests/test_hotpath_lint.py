"""Hot-path synchronization lint: the serving loop must never regrow a
blocking KV copy.

PR 4 moved every bulk KV materialization (host-arena reads/writes, fused
eviction gathers, handoff packing) into ``cache/kv_transfer.py`` — the
ONE module allowed to block on device→host data. This lint pins that
boundary with a source grep: the engine's step/admit code and the
hierarchical cache's match path must not contain the constructs that
silently reintroduce a synchronous copy. A legitimate new sync point
belongs in the staging module (or earns an explicit allowlist entry
here, with a comment defending it)."""

import inspect
import re

import pytest

pytestmark = pytest.mark.quick

# Constructs that force a device→host materialization (or a full device
# sync) when applied to a device array. ``np.asarray(sampled…)`` — the
# designed one-sync-per-launch points — survive because they are matched
# against KV-movement call patterns, not against every asarray.
BANNED = {
    # A full device sync anywhere in the scheduler is a stall by
    # definition; the only block_until_ready in the repo belongs to
    # benches and tests.
    r"\.block_until_ready\(": "explicit device sync",
    r"jax\.device_get\(": "blocking device→host copy",
    # Materializing a pool gather on the host: the write-back / handoff
    # stall this PR removed. (Device-side pool.gather feeding another
    # device op — e.g. the dense-prefill cached-prefix gather — stays
    # legal; wrapping it in np.asarray is not.)
    r"(?<!j)np\.asarray\(\s*(?:self\.)?pool\.gather": "host-materialized pool gather",
    r"gather_padded\(": "fused host gather (staging-module-only)",
    # Reading the host arena inline (the synchronous restore stall).
    r"(?:self\.)?host\.read\(": "host-arena read (staging/restore-path-only)",
}


def _source_of(*objects) -> str:
    return "\n".join(inspect.getsource(o) for o in objects)


def _violations(src: str, banned: dict) -> list[str]:
    out = []
    for pattern, why in banned.items():
        for m in re.finditer(pattern, src):
            line = src[: m.start()].count("\n") + 1
            out.append(f"line ~{line}: {m.group(0)!r} — {why}")
    return out


class TestHotPathSyncLint:
    def test_engine_step_admit_paths_have_no_blocking_kv_copies(self):
        from radixmesh_tpu.engine import engine as engine_mod

        src = _source_of(engine_mod)
        assert not _violations(src, BANNED), "\n".join(_violations(src, BANNED))

    def test_host_cache_match_path_stays_dispatch_only(self):
        """``match_and_load`` may read the arena (that is the documented
        synchronous fallback) but must not host-materialize device
        arrays; the fused sweep gather lives in the flush/plane seam."""
        from radixmesh_tpu.cache.host_cache import HierarchicalCache

        src = _source_of(
            HierarchicalCache.match_and_load,
            HierarchicalCache._writeback,
            HierarchicalCache._evict_host,
        )
        banned = {
            r"(?<!j)np\.asarray\(\s*(?:self\.)?pool\.gather": "host-materialized gather",
            r"gather_padded\(": "per-node gather (must be sweep-fused)",
            r"\.block_until_ready\(": "explicit device sync",
            r"jax\.device_get\(": "blocking device→host copy",
        }
        assert not _violations(src, banned), "\n".join(_violations(src, banned))

    def test_disagg_admit_has_no_host_materialization(self):
        """The decode-side admit writes staged blocks; materializing a
        packet back to numpy there would undo the reader-thread
        staging."""
        from radixmesh_tpu.engine.disagg import DecodeWorker

        src = _source_of(DecodeWorker._admit_one)
        banned = {
            r"(?<!j)np\.asarray\(": "host materialization in the admit path",
            r"\.block_until_ready\(": "explicit device sync",
            r"jax\.device_get\(": "blocking device→host copy",
        }
        assert not _violations(src, banned), "\n".join(_violations(src, banned))

    def test_staging_module_is_the_only_sync_owner(self):
        """Positive control: the constructs ARE present in the staging
        module (the lint greps for real patterns, not typos)."""
        from radixmesh_tpu.cache import kv_transfer

        src = inspect.getsource(kv_transfer)
        assert re.search(r"(?<!j)np\.asarray\(", src)
        assert re.search(r"host\.read\(", src)
