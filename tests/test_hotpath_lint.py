"""Hot-path synchronization lint, running through the meshcheck
framework: the serving loop must never regrow a blocking KV copy.

PR 4 moved every bulk KV materialization into ``cache/kv_transfer.py``
— the ONE module allowed to block on device→host data. The old version
of this file pinned that boundary with regex greps over three scopes;
the ``hot-path`` checker (``radixmesh_tpu/analysis/hot_path.py``) now
enforces the same scoped bans off the AST (invariant ``hotpath-sync``)
PLUS what a scope-grep cannot see: a blocking call N frames down the
call graph from ``Engine.step`` / ``match_prefix`` / admission / oplog
receive (invariant ``hotpath-blocking``). Test names preserved; each
asserts its slice of the checker's findings is empty."""

import ast

import pytest

from radixmesh_tpu.analysis import check_tree as _result
from radixmesh_tpu.analysis import tree_index as _index

pytestmark = pytest.mark.quick


def _sync_findings(rel: str):
    return [
        f for f in _result().findings
        if f.invariant in ("hotpath-sync", "hotpath-blocking") and f.file == rel
    ]


class TestHotPathSyncLint:
    def test_engine_step_admit_paths_have_no_blocking_kv_copies(self):
        bad = _sync_findings("engine/engine.py")
        assert not bad, "\n".join(str(f) for f in bad)

    def test_host_cache_match_path_stays_dispatch_only(self):
        """``match_and_load`` may read the arena (that is the documented
        synchronous fallback — the checker's host_cache scope bans the
        gather/sync constructs, not ``host.read``); the fused sweep
        gather lives in the flush/plane seam."""
        bad = _sync_findings("cache/host_cache.py")
        assert not bad, "\n".join(str(f) for f in bad)

    def test_disagg_admit_has_no_host_materialization(self):
        """The decode-side admit writes staged blocks; materializing a
        packet back to numpy there would undo the reader-thread
        staging. (The checker's disagg scope bans ANY np.asarray in
        ``_admit_one``.)"""
        bad = _sync_findings("engine/disagg.py")
        assert not bad, "\n".join(str(f) for f in bad)

    def test_nothing_reachable_from_serving_entry_points_blocks(self):
        """The grep-invisible half: across the WHOLE package, no
        function reachable from the serving entry points contains an
        unbounded wait/sleep/device-sync."""
        bad = [
            f for f in _result().findings
            if f.invariant == "hotpath-blocking"
        ]
        assert not bad, "\n".join(str(f) for f in bad)

    def test_entry_points_and_thread_map_share_one_call_graph(self):
        """PR 11 refactor guard: the hot-path checker, the thread map,
        and guarded-by all resolve through ONE CallGraph per index
        (analysis/callgraph.py) — a second derivation could silently
        diverge on resolution rules, and the whole point of the shared
        substrate is that a reachability fact proven for one checker
        holds for all of them."""
        from radixmesh_tpu.analysis.callgraph import get_callgraph
        from radixmesh_tpu.analysis.hot_path import DEFAULT_ENTRY_POINTS

        index = _index()
        cg = get_callgraph(index)
        assert get_callgraph(index) is cg  # memoized on the index
        # The serving entry points resolve in the same graph the thread
        # map used, and each reaches a non-trivial frame set.
        for ep in DEFAULT_ENTRY_POINTS:
            assert ep in cg.funcs, f"entry point {ep} not in the call graph"
        reachable, _chains = cg.reach(DEFAULT_ENTRY_POINTS)
        assert len(reachable) > 50, "serving call graph collapsed"

    def test_staging_module_is_the_only_sync_owner(self):
        """Positive control: the banned constructs ARE present in the
        staging module (the checker scopes ban real patterns, not
        typos) — and the staging module itself is exempt by design."""
        tree = _index().module("cache/kv_transfer.py").tree
        names = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Attribute):
                    names.add(f.attr)
                elif isinstance(f, ast.Name):
                    names.add(f.id)
        assert "asarray" in names, "kv_transfer no longer materializes?"
        assert "read" in names, "kv_transfer no longer reads the arena?"
        assert not _sync_findings("cache/kv_transfer.py")

    def test_token_timeline_rides_the_hot_path_clean(self):
        """PR 18: the token timeline's ``note_token`` runs once per
        decoded token INSIDE the serving loop — the speedometer module
        must carry zero blocking findings, and the call graph must
        actually see it from the engine entry points (otherwise the
        reachability guarantee above is vacuous for the newest
        per-token code)."""
        assert not _sync_findings("obs/token_timeline.py")
        from radixmesh_tpu.analysis.callgraph import get_callgraph
        from radixmesh_tpu.analysis.hot_path import DEFAULT_ENTRY_POINTS

        cg = get_callgraph(_index())
        reachable, _chains = cg.reach(DEFAULT_ENTRY_POINTS)
        hits = {fn[1] for fn in reachable if "note_token" in fn[1]}
        assert "TokenTimeline.note_token" in hits
        assert "GoodputLedger.note_token" in hits

    def test_wave_scheduler_and_paged_dispatch_ride_the_hot_path_clean(self):
        """PR 19: the wave scheduler's ``plan``/``note`` run once per
        compute wave and the paged/dense crossover once per decode
        launch — both INSIDE the serving loop. Zero blocking findings
        in the new policy module, and the call graph must actually see
        both seams from the engine entry points (a wave scheduler the
        reachability proof can't see would make the starvation bound
        unauditable)."""
        assert not _sync_findings("engine/waves.py")
        from radixmesh_tpu.analysis.callgraph import get_callgraph
        from radixmesh_tpu.analysis.hot_path import DEFAULT_ENTRY_POINTS

        cg = get_callgraph(_index())
        reachable, _chains = cg.reach(DEFAULT_ENTRY_POINTS)
        names = {fn[1] for fn in reachable}
        assert "WaveScheduler.plan" in names
        assert "WaveScheduler.note" in names
        assert {n for n in names if "select_paged" in n}, (
            "the paged/dense crossover is not on the serving call graph"
        )
