"""Long-context chunked prefill (SURVEY §5; VERDICT round-1 gap #4).

The dense prefill path materializes O(S²) scores — a 32k prompt would
need a ~32768² score tensor per head. The chunked path
(``prefill_chunk_paged`` + ``Engine._prefill_long``) must (a) agree with
the dense path numerically, and (b) admit a 32k prompt at tiny-model
scale with peak memory O(S · chunk).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from radixmesh_tpu.engine.engine import Engine
from radixmesh_tpu.engine.request import SamplingParams
from radixmesh_tpu.models.llama import (
    ModelConfig,
    init_params,
    prefill_chunk_paged,
    prefill_forward,
)

CFG = ModelConfig.tiny()
PARAMS = init_params(CFG, jax.random.PRNGKey(0))
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=4)


def test_chunked_matches_dense_prefill():
    """Chunk-by-chunk paged prefill reproduces the dense path's logits."""
    rng = np.random.default_rng(0)
    S, C, page = 40, 16, 4
    prompt = rng.integers(1, CFG.vocab_size, size=S).astype(np.int32)

    tok = jnp.asarray(prompt)[None]
    pos = jnp.arange(S, dtype=jnp.int32)[None]
    empty = jnp.zeros((CFG.n_layers, 1, 0, CFG.n_kv_heads, CFG.head_dim), CFG.dtype)
    want, _, _ = prefill_forward(
        PARAMS, CFG, tok, pos, empty, empty, jnp.zeros((1,), jnp.int32)
    )

    num_slots = 256
    pool = jnp.zeros(
        (2, CFG.n_layers, CFG.n_kv_heads, num_slots, CFG.head_dim), CFG.dtype
    )
    maxp = 16
    pt = jnp.asarray((np.arange(maxp) + 3).astype(np.int32))[None]
    slots_all = (np.asarray(pt[0])[:, None] * page + np.arange(page)).reshape(-1)
    outs = []
    for start in range(0, S, C):
        n = min(C, S - start)
        toks = np.zeros((1, C), np.int32)
        toks[0, :n] = prompt[start : start + n]
        poss = (start + np.arange(C, dtype=np.int32))[None]
        sl = np.zeros((1, C), np.int32)
        sl[0, :n] = slots_all[start : start + n]
        logits, pool = prefill_chunk_paged(
            PARAMS, CFG, jnp.asarray(toks), jnp.asarray(poss), pool,
            jnp.asarray(sl), pt, jnp.asarray([start + n], jnp.int32),
            page_size=page, kv_block_pages=4,
        )
        outs.append(np.asarray(logits[0, :n], np.float32))
    got = np.concatenate(outs)
    np.testing.assert_allclose(
        got, np.asarray(want[0], np.float32), rtol=2e-2, atol=2e-2
    )


def test_engine_long_path_same_output_as_dense():
    """Greedy generation through the chunked admission path equals the
    dense path's output (same params, same prompt)."""
    prompt = np.random.default_rng(1).integers(1, CFG.vocab_size, 96).tolist()
    dense = Engine(CFG, PARAMS, num_slots=2048, page_size=4, max_batch=2,
                   long_prefill_threshold=10_000)
    out_d = dense.generate([prompt], GREEDY)[0]
    chunked = Engine(CFG, PARAMS, num_slots=2048, page_size=4, max_batch=2,
                     prefill_chunk=32, long_prefill_threshold=16)
    out_c = chunked.generate([prompt], GREEDY)[0]
    assert out_d == out_c
    assert chunked.stats.prompt_tokens == len(prompt)


def test_32k_prompt_prefills():
    """The VERDICT gate: a 32k-token prompt admits and generates without
    ever materializing O(S²) scores (the dense path at this length would
    need a >4-billion-element score tensor per head; peak live memory here
    is the pool + O(chunk·block) activations)."""
    cfg = CFG.replace(max_seq_len=34_000)
    S = 32_768
    engine = Engine(
        cfg, PARAMS, num_slots=S + 2048, page_size=16, max_batch=2,
        prefill_chunk=2048, long_prefill_threshold=4096,
    )
    prompt = np.random.default_rng(2).integers(1, cfg.vocab_size, S).tolist()
    out = engine.generate([prompt], SamplingParams(temperature=0.0, max_new_tokens=2))[0]
    assert len(out) == 2
    assert engine.stats.prompt_tokens == S
    # The full context is live in the paged pool (32768 tokens of KV).
    req_pages = -(-S // 16)
    assert engine.pool.free_slots <= engine.pool.num_slots - req_pages * 16

    # Follow-up sharing the 32k prefix is an (almost) total cache hit.
    follow = prompt + [7, 8, 9]
    out2 = engine.generate(
        [follow], SamplingParams(temperature=0.0, max_new_tokens=2)
    )[0]
    assert len(out2) == 2
    assert engine.stats.cached_tokens >= S - 16  # page-aligned reuse


def test_chunked_kernel_engaged_matches_dense_prefill():
    """The same chunk-by-chunk walk with the Pallas chunk kernel forced
    (interpret mode executes the kernel program on CPU): logits must
    match the dense path exactly like the jnp hybrid does (VERDICT
    round-3 next-step #3 — prefill-side kernelization)."""
    rng = np.random.default_rng(2)
    S, C, page = 40, 16, 4
    prompt = rng.integers(1, CFG.vocab_size, size=S).astype(np.int32)

    tok = jnp.asarray(prompt)[None]
    pos = jnp.arange(S, dtype=jnp.int32)[None]
    empty = jnp.zeros((CFG.n_layers, 1, 0, CFG.n_kv_heads, CFG.head_dim), CFG.dtype)
    want, _, _ = prefill_forward(
        PARAMS, CFG, tok, pos, empty, empty, jnp.zeros((1,), jnp.int32)
    )

    num_slots = 256
    pool = jnp.zeros(
        (2, CFG.n_layers, CFG.n_kv_heads, num_slots, CFG.head_dim), CFG.dtype
    )
    maxp = 16
    pt = jnp.asarray((np.arange(maxp) + 3).astype(np.int32))[None]
    slots_all = (np.asarray(pt[0])[:, None] * page + np.arange(page)).reshape(-1)
    outs = []
    for start in range(0, S, C):
        n = min(C, S - start)
        toks = np.zeros((1, C), np.int32)
        toks[0, :n] = prompt[start : start + n]
        poss = (start + np.arange(C, dtype=np.int32))[None]
        sl = np.zeros((1, C), np.int32)
        sl[0, :n] = slots_all[start : start + n]
        logits, pool = prefill_chunk_paged(
            PARAMS, CFG, jnp.asarray(toks), jnp.asarray(poss), pool,
            jnp.asarray(sl), pt, jnp.asarray([start + n], jnp.int32),
            page_size=page, kv_block_pages=4,
            use_kernel=True, interpret=True,
        )
        outs.append(np.asarray(logits[0, :n], np.float32))
    got = np.concatenate(outs)
    np.testing.assert_allclose(
        got, np.asarray(want[0], np.float32), rtol=2e-2, atol=2e-2
    )
