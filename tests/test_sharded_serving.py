"""Sharded serving (SURVEY §7 stage 7; VERDICT round-1 weak #4).

The serving hot path — Engine scheduler + paged pool + decode_step — must
run unchanged on a multi-device mesh: params tp-sharded, pool sharded on
the kv-head axis, GSPMD partitioning the jnp ops and shard_map carrying
the Pallas kernel. Runs on the 8-device virtual CPU mesh (conftest).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from radixmesh_tpu.engine.engine import Engine
from radixmesh_tpu.engine.request import SamplingParams
from radixmesh_tpu.models.llama import ModelConfig, init_params
from radixmesh_tpu.ops.attention import (
    attend_decode_ref,
    paged_attention_pool_kernel_sharded,
)
from radixmesh_tpu.parallel.sharding import MeshPlan, make_mesh

CFG = ModelConfig.tiny().replace(n_heads=4, n_kv_heads=4)
PARAMS = init_params(CFG, jax.random.PRNGKey(0))
GREEDY = SamplingParams(temperature=0.0, max_new_tokens=6)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshPlan(dp=1, sp=2, tp=4))


def test_sharded_engine_matches_single_device(mesh):
    """Same greedy tokens with and without the mesh: sharding changes
    array placement, not semantics."""
    prompts = [
        np.random.default_rng(0).integers(1, CFG.vocab_size, 24).tolist(),
        np.random.default_rng(1).integers(1, CFG.vocab_size, 17).tolist(),
    ]
    single = Engine(CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4)
    want = single.generate(prompts, GREEDY)
    sharded = Engine(
        CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4, device_mesh=mesh
    )
    got = sharded.generate(prompts, GREEDY)
    assert want == got


def test_sharded_prefix_hit(mesh):
    """Cache publish + reuse work against the kv-head-sharded pool."""
    engine = Engine(
        CFG, PARAMS, num_slots=1024, page_size=4, max_batch=4, device_mesh=mesh
    )
    prompt = list(range(1, 25))
    engine.generate([prompt], GREEDY)
    engine.generate([prompt + [100, 101]], GREEDY)
    assert engine.stats.cached_tokens >= 24


def test_tp_divisibility_validated(mesh):
    bad = ModelConfig.tiny()  # 2 kv heads, tp=4
    with pytest.raises(ValueError, match="divide tp"):
        Engine(bad, init_params(bad, jax.random.PRNGKey(0)), device_mesh=mesh)


def test_shard_map_kernel_matches_oracle(mesh):
    """The shard_map'd Pallas pool kernel (interpret mode on the CPU mesh)
    agrees with the gather oracle — validates the tp partitioning specs
    independently of Mosaic."""
    rng = np.random.default_rng(3)
    B, Hq, Hkv, D, page, P_, L = 2, 8, 4, 128, 8, 16, 2
    max_pages = 4
    q = jnp.asarray(rng.normal(size=(B, Hq, D)), jnp.float32)
    kv = jnp.asarray(rng.normal(size=(2, L, Hkv, P_, page, D)), jnp.float32)
    pt = jnp.asarray(
        rng.permutation(P_)[: B * max_pages].reshape(B, max_pages), jnp.int32
    )
    ln = jnp.asarray([3, max_pages * page], jnp.int32)
    layer = 1
    pages = kv.reshape(2, L, Hkv, P_, page, D)
    want = attend_decode_ref(q, pages[0, layer], pages[1, layer], pt, ln)
    got = paged_attention_pool_kernel_sharded(
        q, pages, pt, ln, layer, mesh, interpret=True
    )
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=2e-5, atol=2e-5,
    )


class TestSequenceParallelPrefill:
    """SP serving prefill (SURVEY §5 serving-side; VERDICT round-1 item 31
    'nothing in models/ or engine/ calls them'): a fresh long prompt
    prefills sequence-sharded via ring attention over the sp axis."""

    def test_sp_prefill_matches_dense(self, mesh):
        from radixmesh_tpu.models.llama import prefill_forward, prefill_forward_sp

        cfg = CFG.replace(dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(3))
        S = 64
        tokens = jnp.asarray(
            np.random.default_rng(2).integers(1, cfg.vocab_size, (2, S)),
            jnp.int32,
        )
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (2, S))
        got, gk, gv = prefill_forward_sp(params, cfg, tokens, positions, mesh)
        empty = jnp.zeros((cfg.n_layers, 2, 0, cfg.n_kv_heads, cfg.head_dim),
                          cfg.dtype)
        want, wk, wv = prefill_forward(
            params, cfg, tokens, positions, empty, empty,
            jnp.zeros((2,), jnp.int32),
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )
        np.testing.assert_allclose(np.asarray(gk), np.asarray(wk), rtol=2e-4,
                                   atol=2e-4)

    def test_engine_sp_prefill_end_to_end(self, mesh):
        """An engine on the mesh routes a fresh long prompt through the
        sp path and its published KV is a valid cache for a follow-up."""
        cfg = CFG.replace(dtype=jnp.float32)
        params = init_params(cfg, jax.random.PRNGKey(3))
        eng = Engine(
            cfg, params, num_slots=2048, page_size=4, max_batch=2,
            device_mesh=mesh, sp_prefill_threshold=48,
        )
        single = Engine(cfg, params, num_slots=2048, page_size=4, max_batch=2)
        prompt = np.random.default_rng(5).integers(1, cfg.vocab_size, 60).tolist()
        out_sp = eng.generate([prompt], GREEDY)[0]
        out_single = single.generate([prompt], GREEDY)[0]
        assert out_sp == out_single
        # Follow-up hits the cache published by the sp prefill.
        cached_before = eng.stats.cached_tokens
        out2 = eng.generate([prompt + [9, 8]], GREEDY)[0]
        assert len(out2) == 6
        assert eng.stats.cached_tokens - cached_before >= 56
