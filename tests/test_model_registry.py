"""Model registry: every preset the serving CLI exposes must build a
well-formed config whose abstract parameter tree matches its family's
published size class (no 72B of RAM needed — ``jax.eval_shape``), and
the tied-embeddings variants (Llama-3.2) must match real ``transformers``
numerics like the untied families do."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from radixmesh_tpu.models import get_config, init_params
from radixmesh_tpu.models.llama import ModelConfig, prefill_forward

# preset -> (min, max) expected parameter count, in billions.
_SIZES = {
    "llama3-8b": (7.5, 8.5),
    "llama3-70b": (69, 72),
    "llama3.1-8b": (7.5, 8.5),
    "llama3.1-70b": (69, 72),
    "llama3.2-1b": (1.1, 1.4),
    "llama3.2-3b": (3.0, 3.5),
    "qwen2-7b": (7.2, 8.0),
    "qwen2-72b": (71, 74),
    "qwen2.5-14b": (14, 15.5),
    "qwen2.5-32b": (31, 34),
}


def test_hf_config_parity_facts():
    """Config-level facts that diverge between sibling checkpoints and
    silently corrupt numerics if copy-pasted (the eval_shape size checks
    can't see them): rope scaling is a 3.1-generation feature, and
    Qwen2.5's mid sizes use a different rms eps than 7B/72B."""
    assert get_config("llama3-8b").rope_scaling is None
    assert get_config("llama3-8b").max_seq_len == 8192
    assert get_config("llama3.1-8b").rope_scaling is not None
    assert get_config("llama3.1-8b").max_seq_len == 131072
    assert get_config("llama3-70b").rope_scaling is None
    assert get_config("llama3-70b").max_seq_len == 8192
    assert get_config("llama3.1-70b").rope_scaling is not None
    assert get_config("llama3.1-70b").max_seq_len == 131072
    assert get_config("qwen2-7b").rms_eps == 1e-6
    assert get_config("qwen2.5-14b").rms_eps == 1e-5
    assert get_config("qwen2.5-32b").rms_eps == 1e-5
    # Tied embeddings are a 3.2 feature only.
    assert get_config("llama3.2-1b").tie_embeddings
    assert not get_config("llama3-8b").tie_embeddings


@pytest.mark.parametrize("preset", sorted(_SIZES))
def test_preset_param_count(preset):
    cfg = get_config(preset)
    abstract = jax.eval_shape(
        lambda k: init_params(cfg, k), jax.random.PRNGKey(0)
    )
    n = sum(int(np.prod(l.shape)) for l in jax.tree.leaves(abstract))
    lo, hi = _SIZES[preset]
    assert lo * 1e9 < n < hi * 1e9, f"{preset}: {n/1e9:.2f}B params"
    if cfg.tie_embeddings:
        assert "lm_head" not in abstract


def test_unknown_preset_lists_known():
    with pytest.raises(ValueError, match="unknown model"):
        get_config("gpt-5")


def test_overrides_apply():
    cfg = get_config("llama3-8b", n_layers=2, max_seq_len=1024)
    assert cfg.n_layers == 2 and cfg.max_seq_len == 1024


def test_tied_embeddings_matches_transformers(tmp_path):
    """Llama-3.2's tie_word_embeddings path: a real HF checkpoint with
    tied weights loads through hf_io and our logits match HF's."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig, LlamaForCausalLM

    from radixmesh_tpu.models.hf_io import load_hf_checkpoint

    hf_cfg = LlamaConfig(
        vocab_size=512, hidden_size=128, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2, intermediate_size=256,
        rope_theta=10000.0, rms_norm_eps=1e-5, max_position_embeddings=512,
        tie_word_embeddings=True, attention_bias=False, use_cache=False,
    )
    torch.manual_seed(11)
    model = LlamaForCausalLM(hf_cfg).to(torch.float32).eval()
    ckpt = tmp_path / "tied"
    model.save_pretrained(ckpt, safe_serialization=True)

    cfg = ModelConfig(
        vocab_size=512, hidden=128, n_layers=2, n_heads=4, n_kv_heads=2,
        head_dim=32, intermediate=256, rope_theta=10000.0,
        rope_scaling=None, max_seq_len=512, tie_embeddings=True,
        dtype=jnp.float32,
    )
    params = load_hf_checkpoint(str(ckpt), cfg)
    assert "lm_head" not in params

    ids = [3, 141, 59, 26, 250, 8]
    toks = jnp.asarray([ids], jnp.int32)
    pos = jnp.arange(len(ids), dtype=jnp.int32)[None]
    empty = jnp.zeros((cfg.n_layers, 1, 0, cfg.n_kv_heads, cfg.head_dim),
                      cfg.dtype)
    ours, _, _ = prefill_forward(
        params, cfg, toks, pos, empty, empty, jnp.zeros((1,), jnp.int32)
    )
    with torch.no_grad():
        theirs = model(torch.tensor([ids])).logits[0].float().numpy()
    np.testing.assert_allclose(
        np.asarray(ours[0], np.float32), theirs, rtol=2e-4, atol=2e-4
    )
