"""Ring topology policy + conflict resolver tests (reference
``policy/sync_algo.py`` / ``conflict_resolve.py`` semantics)."""

import pytest

from radixmesh_tpu.config import MeshConfig
from radixmesh_tpu.policy import NodeRankConflictResolver, RingSyncAlgo, get_sync_algo


def cfg(local, prefill=3, decode=2, router=1):
    return MeshConfig(
        prefill_nodes=[f"p{i}" for i in range(prefill)],
        decode_nodes=[f"d{i}" for i in range(decode)],
        router_nodes=[f"r{i}" for i in range(router)],
        local_addr=local,
    )


class TestRingSyncAlgo:
    def setup_method(self):
        self.algo = RingSyncAlgo()

    def test_ring_order_and_successor(self):
        c = cfg("p0")
        assert self.algo.ring(c) == ["p0", "p1", "p2", "d0", "d1"]
        assert self.algo.topo(c).next_node == "p1"
        assert self.algo.topo(cfg("p2")).next_node == "d0"
        # Last decode node wraps to first prefill node.
        assert self.algo.topo(cfg("d1")).next_node == "p0"

    def test_master_fans_out_to_routers(self):
        assert self.algo.topo(cfg("p0")).routers == ["r0"]
        for other in ("p1", "p2", "d0", "d1"):
            assert self.algo.topo(cfg(other)).routers == []

    def test_router_outside_ring(self):
        t = self.algo.topo(cfg("r0"))
        assert t.next_node is None and t.routers == []
        assert not self.algo.can_send(cfg("r0"))
        assert self.algo.can_recv(cfg("r0"))

    def test_full_ring_reaches_everyone_within_ttl(self):
        # Walking data_ttl hops from any origin visits every ring member.
        c = cfg("p0")
        ring = self.algo.ring(c)
        ttl = self.algo.data_ttl(c)
        for start in range(len(ring)):
            seen = {ring[(start + i) % len(ring)] for i in range(ttl)}
            assert seen == set(ring)

    def test_ttls(self):
        c = cfg("p0")
        assert self.algo.data_ttl(c) == 5
        assert self.algo.tick_ttl(c) == 10
        assert self.algo.gc_ttl(c) == 5

    def test_tick_origin(self):
        # Initial origin = first decode node (global rank num_prefill).
        assert self.algo.tick_origin_rank(cfg("d0")) == 3
        # No decode nodes -> master ticks (fallback beyond the reference).
        no_decode = MeshConfig(
            prefill_nodes=["p0", "p1"], decode_nodes=[], local_addr="p0"
        )
        assert self.algo.tick_origin_rank(no_decode) == 0

    def test_factory(self):
        assert isinstance(get_sync_algo("ring"), RingSyncAlgo)
        with pytest.raises(ValueError):
            get_sync_algo("star")


class TestConflictResolver:
    def test_lowest_rank_wins(self):
        keep = NodeRankConflictResolver.keep
        assert keep(0, 1)  # existing lower -> keep existing
        assert keep(2, 2)  # tie -> keep existing (stability)
        assert not keep(3, 1)  # new lower -> replace

    def test_total_order_convergence(self):
        # Whatever order writes arrive in, the surviving rank is the min —
        # the property that makes master-free replication converge.
        import itertools

        for perm in itertools.permutations([3, 1, 2, 0]):
            survivor = perm[0]
            for new in perm[1:]:
                if not NodeRankConflictResolver.keep(survivor, new):
                    survivor = new
            assert survivor == 0
