"""SLO control plane (``radixmesh_tpu/slo/``): admission, fairness,
deadline shedding, degradation tiers — the policy state machine under a
virtual clock, plus the :class:`SLORunner` wired around a real engine.

Every controller test drives :class:`OverloadController` with an injected
clock, so behavior is exactly reproducible; the runner tests use the tiny
fp32 model from ``test_engine.py`` on CPU."""

import numpy as np
import pytest

from radixmesh_tpu.engine.request import Request, RequestState, SamplingParams
from radixmesh_tpu.obs.metrics import get_registry
from radixmesh_tpu.slo.control import (
    SHED_DEADLINE,
    SHED_DISPATCH_DEADLINE,
    SHED_OVER_BURST,
    SHED_QUEUE_FULL,
    SHED_RATE_LIMITED,
    OverloadController,
    RequestShed,
    SLOConfig,
    TenantConfig,
)

pytestmark = pytest.mark.quick


class Clock:
    """Manually-advanced monotonic clock."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def make_req(tenant: str, n_tokens: int, submit_time: float,
             ttft_deadline_s=None, max_new_tokens=8) -> Request:
    req = Request(
        prompt=np.arange(1, n_tokens + 1, dtype=np.int32),
        sampling=SamplingParams(max_new_tokens=max_new_tokens),
        tenant=tenant,
        ttft_deadline_s=ttft_deadline_s,
    )
    req.submit_time = submit_time
    return req


def offer_and_enqueue(ctl, clock, tenant, n_tokens, ttft_deadline_s=None):
    dec = ctl.offer(tenant, n_tokens, ttft_deadline_s, now=clock())
    if dec.admitted:
        req = make_req(tenant, n_tokens, clock(), ttft_deadline_s)
        ctl.enqueue(req, now=clock())
        return req
    return None


class TestTokenBucket:
    def test_burst_then_rate_limits(self):
        clock = Clock()
        cfg = SLOConfig(
            tenants={
                "t": TenantConfig(rate_tokens_per_s=100, burst_tokens=250)
            }
        )
        ctl = OverloadController(cfg, clock=clock)
        # Burst depth covers two 100-token requests; the third sheds.
        assert ctl.offer("t", 100, now=clock()).admitted
        assert ctl.offer("t", 100, now=clock()).admitted
        dec = ctl.offer("t", 100, now=clock())
        assert not dec.admitted
        assert dec.reason == SHED_RATE_LIMITED
        # retry_after covers the deficit: 50 tokens short at 100 tok/s.
        assert dec.retry_after_s == pytest.approx(0.5)
        # Refill at the provisioned rate re-admits.
        clock.advance(0.6)
        assert ctl.offer("t", 100, now=clock()).admitted

    def test_over_burst_prompt_is_nonretriable_413(self):
        """A prompt the bucket can NEVER hold must not get a retriable
        429 (the client would loop forever) — and must not spend any
        rate budget on the way out."""
        clock = Clock()
        cfg = SLOConfig(
            tenants={"t": TenantConfig(rate_tokens_per_s=100)}
        )  # burst defaults to one second of rate = 100 tokens
        ctl = OverloadController(cfg, clock=clock)
        dec = ctl.offer("t", 150, now=clock())
        assert not dec.admitted and dec.reason == SHED_OVER_BURST
        assert dec.retry_after_s is None
        assert RequestShed(dec.reason, None, "t").http_status == 413
        # The refusal spent nothing: a full-burst prompt still admits.
        assert ctl.offer("t", 100, now=clock()).admitted

    def test_unlimited_tenant_never_rate_sheds(self):
        clock = Clock()
        ctl = OverloadController(SLOConfig(), clock=clock)
        for _ in range(100):
            assert ctl.offer("anyone", 10_000, now=clock()).admitted

    def test_queue_full_sheds(self):
        clock = Clock()
        ctl = OverloadController(
            SLOConfig(max_queue_requests=2), clock=clock
        )
        assert offer_and_enqueue(ctl, clock, "a", 8) is not None
        assert offer_and_enqueue(ctl, clock, "a", 8) is not None
        dec = ctl.offer("a", 8, now=clock())
        assert not dec.admitted and dec.reason == SHED_QUEUE_FULL


class TestWeightedFairQueue:
    def test_dispatch_order_tracks_weights(self):
        """With both tenants backlogged, dispatched token shares follow
        the 3:1 weight ratio — start-time fair queueing's guarantee."""
        clock = Clock()
        cfg = SLOConfig(
            tenants={
                "heavy": TenantConfig(weight=3.0),
                "light": TenantConfig(weight=1.0),
            }
        )
        ctl = OverloadController(cfg, clock=clock)
        for _ in range(40):
            offer_and_enqueue(ctl, clock, "heavy", 10)
            offer_and_enqueue(ctl, clock, "light", 10)
        served = {"heavy": 0, "light": 0}
        for _ in range(20):  # drain only half: the backlogged regime
            req = ctl.pop_ready(now=clock())
            served[req.tenant] += len(req.prompt)
        assert served["heavy"] + served["light"] == 200
        # 3:1 entitlement → heavy gets 150 of 200 (±1 request of rounding).
        assert abs(served["heavy"] - 150) <= 10

    def test_fifo_within_tenant(self):
        clock = Clock()
        ctl = OverloadController(SLOConfig(), clock=clock)
        reqs = [offer_and_enqueue(ctl, clock, "a", 8) for _ in range(5)]
        popped = [ctl.pop_ready(now=clock()) for _ in range(5)]
        assert [r.rid for r in popped] == [r.rid for r in reqs]

    def test_bursty_tenant_cannot_convoy_steady_one(self):
        """A 100-request burst queued FIRST must not serialize ahead of a
        single later arrival from an equal-weight tenant: virtual finish
        times interleave the steady tenant near the front."""
        clock = Clock()
        ctl = OverloadController(SLOConfig(), clock=clock)
        for _ in range(100):
            offer_and_enqueue(ctl, clock, "bursty", 10)
        late = offer_and_enqueue(ctl, clock, "steady", 10)
        position = None
        for i in range(101):
            if ctl.pop_ready(now=clock()) is late:
                position = i
                break
        assert position is not None and position <= 2


class TestDeadlineAdmission:
    def test_uncalibrated_admits_everything(self):
        clock = Clock()
        ctl = OverloadController(SLOConfig(), clock=clock)
        # No EWMA yet: no wait estimate exists, so nothing deadline-sheds.
        assert ctl.offer("a", 10_000, ttft_deadline_s=0.001, now=clock()).admitted

    def test_sheds_when_backlog_exceeds_deadline(self):
        clock = Clock()
        ctl = OverloadController(SLOConfig(), clock=clock)
        ctl.observe_service(1000, 1.0)  # 1000 tok/s
        # 2000 backlogged tokens ≈ 2 s of queue ahead.
        for _ in range(20):
            offer_and_enqueue(ctl, clock, "a", 100)
        dec = ctl.offer("a", 100, ttft_deadline_s=0.5, now=clock())
        assert not dec.admitted and dec.reason == SHED_DEADLINE
        assert dec.retry_after_s > 0
        # A deadline generous enough for the backlog still admits.
        assert ctl.offer("a", 100, ttft_deadline_s=10.0, now=clock()).admitted

    def test_dispatch_time_recheck_drops_stale_requests(self):
        """A request that waited past its deadline in queue is dropped at
        pop time — it never occupies a batch row."""
        clock = Clock()
        ctl = OverloadController(SLOConfig(), clock=clock)
        ctl.observe_service(1000, 1.0)
        stale = offer_and_enqueue(ctl, clock, "a", 100, ttft_deadline_s=0.5)
        fresh = offer_and_enqueue(ctl, clock, "a", 100)  # no deadline
        clock.advance(1.0)  # stale's deadline has passed
        got = ctl.pop_ready(now=clock())
        assert got is fresh
        assert stale.shed and stale.shed_reason == SHED_DISPATCH_DEADLINE
        assert ctl.drain_shed() == [stale]

    def test_deadline_shed_does_not_spend_rate_budget(self):
        """A deadline refusal happens BEFORE the bucket take: work that
        was never admitted must not drain the tenant's rate budget into
        spurious 429s once the backlog clears."""
        clock = Clock()
        cfg = SLOConfig(
            tenants={
                "t": TenantConfig(rate_tokens_per_s=100, burst_tokens=200)
            }
        )
        ctl = OverloadController(cfg, clock=clock)
        ctl.observe_service(1000, 1.0)
        # 3 s of dispatched-but-unserved work ahead of any arrival.
        for _ in range(30):
            offer_and_enqueue(ctl, clock, "other", 100)
        while ctl.pop_ready(now=clock()) is not None:
            pass
        for _ in range(5):
            dec = ctl.offer("t", 100, ttft_deadline_s=0.5, now=clock())
            assert not dec.admitted and dec.reason == SHED_DEADLINE
        # Full burst (200 tokens) survived all five refusals.
        assert ctl.offer("t", 100, now=clock()).admitted
        assert ctl.offer("t", 100, now=clock()).admitted

    def test_cancel_before_first_token_retires_backlog(self):
        """note_retired/note_first_token are idempotent per request in
        either order, so a cancel can never leak dispatched tokens into
        the backlog estimate (a leak would inflate est_wait forever and
        disarm the idle-probe escape)."""
        clock = Clock()
        ctl = OverloadController(SLOConfig(), clock=clock)
        ctl.observe_service(1000, 1.0)
        req = offer_and_enqueue(ctl, clock, "a", 500)
        assert ctl.pop_ready(now=clock()) is req
        assert ctl.est_wait_s() == pytest.approx(0.5)
        req.admit_time = clock()
        ctl.note_retired(req, now=clock())
        assert ctl.est_wait_s() == 0.0
        ctl.note_first_token(req, now=clock.advance(0.1))  # late: no-op
        assert ctl._dispatched_tokens == 0
        # Reverse order: first token wins, the retire is a no-op.
        req2 = offer_and_enqueue(ctl, clock, "a", 500)
        assert ctl.pop_ready(now=clock()) is req2
        req2.admit_time = clock()
        ctl.note_first_token(req2, now=clock.advance(0.1))
        ctl.note_retired(req2, now=clock())
        assert ctl._dispatched_tokens == 0

    def test_default_ttft_slo_applies(self):
        clock = Clock()
        ctl = OverloadController(
            SLOConfig(default_ttft_slo_s=0.5), clock=clock
        )
        ctl.observe_service(1000, 1.0)
        for _ in range(20):
            offer_and_enqueue(ctl, clock, "a", 100)
        dec = ctl.offer("a", 100, now=clock())  # carries no deadline
        assert not dec.admitted and dec.reason == SHED_DEADLINE


class TestDegradationTiers:
    def cfg(self):
        return SLOConfig(
            tier_backlog_s=(0.5, 1.5, 3.0),
            tier_up_hold_s=0.1,
            tier_down_hold_s=1.0,
        )

    def test_tier_ladder_up_and_down_with_hysteresis(self):
        clock = Clock()
        ctl = OverloadController(self.cfg(), clock=clock)
        ctl.observe_service(1000, 1.0)
        assert ctl.update_tier(now=clock()) == 0
        # 4 s of backlog: past every threshold, but not yet sustained.
        for _ in range(40):
            offer_and_enqueue(ctl, clock, "a", 100)
        assert ctl.update_tier(now=clock()) == 0
        clock.advance(0.2)  # > tier_up_hold_s
        assert ctl.update_tier(now=clock()) == 3
        # Drain the queue: backlog empties, but the tier holds until the
        # recovery is sustained (tier_down_hold_s).
        drained = 0
        while ctl.pop_ready(now=clock()) is not None:
            drained += 1
        assert drained == 40
        for _ in range(40):  # first tokens retire the backlog tokens
            req = make_req("a", 100, clock())
            req.admit_time = clock()
            ctl.note_first_token(req, now=clock.advance(0.001))
        assert ctl.update_tier(now=clock()) == 3
        clock.advance(1.1)
        assert ctl.update_tier(now=clock()) == 0
        events = ctl.tier_events
        assert [(old, new) for _, old, new, _ in events] == [(0, 3), (3, 0)]

    def test_transient_spike_does_not_flap(self):
        clock = Clock()
        ctl = OverloadController(self.cfg(), clock=clock)
        ctl.observe_service(1000, 1.0)
        reqs = [offer_and_enqueue(ctl, clock, "a", 100) for _ in range(40)]
        # Spike visible for less than tier_up_hold_s, then drained.
        assert ctl.update_tier(now=clock()) == 0
        clock.advance(0.05)
        while ctl.pop_ready(now=clock()) is not None:
            pass
        for r in reqs:
            r.admit_time = clock()
            ctl.note_first_token(r, now=clock())
        clock.advance(0.2)
        assert ctl.update_tier(now=clock()) == 0
        assert ctl.tier_events == []


class TestObservability:
    def test_metrics_exported(self):
        clock = Clock()
        cfg = SLOConfig(
            tenants={"t": TenantConfig(rate_tokens_per_s=10, burst_tokens=10)}
        )
        ctl = OverloadController(cfg, clock=clock)
        req = offer_and_enqueue(ctl, clock, "t", 8)
        assert req is not None
        assert not ctl.offer("t", 8, now=clock()).admitted  # bucket empty
        ctl.pop_ready(now=clock())
        snap = get_registry().snapshot()
        assert snap['radixmesh_slo_admitted_requests_total{tenant="t"}'] == 1
        assert (
            snap['radixmesh_slo_shed_requests_total{reason="rate_limited",tenant="t"}']
            == 1
        )
        assert 'radixmesh_slo_degradation_tier' in snap
        # The exposition endpoint renders the same series.
        text = get_registry().render()
        assert "radixmesh_slo_queue_depth_requests" in text
        assert "radixmesh_slo_admission_wait_seconds_bucket" in text

    def test_snapshot_shape(self):
        ctl = OverloadController(SLOConfig(), clock=Clock())
        snap = ctl.snapshot()
        for key in ("tier", "backlog_tokens", "est_wait_s", "tenants",
                    "total_admitted", "total_shed"):
            assert key in snap


class TestConfigValidation:
    def test_bad_weight(self):
        with pytest.raises(ValueError):
            TenantConfig(weight=0)

    def test_bad_thresholds(self):
        with pytest.raises(ValueError):
            SLOConfig(tier_backlog_s=(3.0, 1.0, 2.0))

    def test_shed_error_http_mapping(self):
        assert RequestShed(SHED_RATE_LIMITED).http_status == 429
        assert RequestShed(SHED_DEADLINE).http_status == 503


# ----------------------------------------------------------------------
# SLORunner over a real engine (tiny fp32 model, CPU)
# ----------------------------------------------------------------------

from tests.test_engine import make_engine, model, oracle_generate  # noqa: F401,E402


class TestSLORunner:
    def test_light_load_is_transparent(self, model):
        """At ≤1× load the SLO layer must change NOTHING: outputs match
        the oracle, nothing sheds, tier stays 0."""
        from radixmesh_tpu.slo.runner import SLORunner

        cfg, params = model
        eng = make_engine(model)
        runner = SLORunner(eng, SLOConfig()).start()
        try:
            rng = np.random.default_rng(5)
            prompts = [
                rng.integers(1, cfg.vocab_size, n).tolist() for n in (7, 13, 19)
            ]
            reqs = [
                runner.submit(p, SamplingParams(max_new_tokens=5), tenant=t)
                for p, t in zip(prompts, ("a", "b", "a"))
            ]
            outs = [runner.wait(r, timeout=120) for r in reqs]
            for p, o in zip(prompts, outs):
                assert o == oracle_generate(cfg, params, p, 5)
            snap = runner.ctl.snapshot()
            assert snap["total_shed"] == 0
            assert snap["tier"] == 0
            assert snap["total_admitted"] == 3
        finally:
            runner.close()

    def test_rate_limited_tenant_sheds_with_retry_after(self, model):
        from radixmesh_tpu.slo.runner import SLORunner

        cfg, _ = model
        eng = make_engine(model)
        # Near-zero refill rate: the bucket must stay empty across however
        # long the first generation takes on a real clock.
        slo = SLOConfig(
            tenants={
                "free": TenantConfig(rate_tokens_per_s=0.1, burst_tokens=24)
            }
        )
        runner = SLORunner(eng, slo).start()
        try:
            rng = np.random.default_rng(6)
            ok = runner.submit(
                rng.integers(1, cfg.vocab_size, 20).tolist(),
                SamplingParams(max_new_tokens=3),
                tenant="free",
            )
            runner.wait(ok, timeout=120)
            with pytest.raises(RequestShed) as exc:
                runner.submit(
                    rng.integers(1, cfg.vocab_size, 20).tolist(),
                    SamplingParams(max_new_tokens=3),
                    tenant="free",
                )
            assert exc.value.http_status == 429
            assert exc.value.retry_after_s > 0
        finally:
            runner.close()

    def test_tier_knobs_apply_and_restore(self, model):
        """Force the controller through the ladder and check the runner
        actually turns engine knobs (spec decode, wave width) and caps
        max_new_tokens — then restores on recovery."""
        from radixmesh_tpu.slo.runner import SLORunner

        cfg, _ = model
        eng = make_engine(model, spec_decode_tokens=3)
        base_wave = eng.prefill_wave_tokens
        clock = Clock()
        slo = SLOConfig(
            tier_backlog_s=(0.5, 1.5, 3.0),
            tier_up_hold_s=0.0,
            tier_down_hold_s=0.5,
            tier2_max_new_tokens=2,
        )
        runner = SLORunner(eng, slo, clock=clock)
        ctl = runner.ctl
        ctl.observe_service(1000, 1.0)
        # 4 s of estimated backlog → tier 3 (hold 0 ⇒ immediate).
        queued = []
        for _ in range(40):
            req = make_req("a", 100, clock(), max_new_tokens=50)
            ctl.enqueue(req, now=clock())
            queued.append(req)
        runner._pump()
        assert runner._applied_tier == 3
        assert eng.spec_decode_tokens == 0
        assert eng.prefill_wave_tokens < base_wave
        # Dispatched requests got the tier-2 output cap. (Identity, not
        # ==: dataclass equality would compare prompt arrays.)
        dispatched = [r for r in queued if any(r is w for w in eng.waiting)]
        assert dispatched and all(
            r.sampling.max_new_tokens == 2 and r.degradation_tier == 3
            for r in dispatched
        )
        # Recovery: drain queues + backlog, hold past tier_down_hold_s
        # (one pump starts the below-threshold timer, the next — after
        # the hold — steps down).
        while ctl.pop_ready(now=clock()) is not None:
            pass
        for r in queued:
            r.admit_time = clock()
            ctl.note_first_token(r, now=clock())
        runner._pump()
        assert runner._applied_tier == 3
        clock.advance(0.6)
        runner._pump()
        assert runner._applied_tier == 0
        assert eng.spec_decode_tokens == 3
        assert eng.prefill_wave_tokens == base_wave
        eng.waiting.clear()  # never stepped; drop the fabricated requests

    def test_e2e_deadline_cancels_running_request(self, model):
        from radixmesh_tpu.slo.runner import SLORunner

        cfg, _ = model
        eng = make_engine(model)
        runner = SLORunner(eng, SLOConfig()).start()
        try:
            rng = np.random.default_rng(8)
            req = runner.submit(
                rng.integers(1, cfg.vocab_size, 10).tolist(),
                SamplingParams(max_new_tokens=10_000_000),
                tenant="a",
                e2e_deadline_s=0.3,
            )
            out = runner.wait(req, timeout=120)
            assert req.cancelled and req.shed_reason == "e2e_deadline"
            assert len(out) < 10_000_000
        finally:
            runner.close()

    def test_close_flushes_queued_requests(self, model):
        from radixmesh_tpu.slo.runner import SLORunner

        cfg, _ = model
        eng = make_engine(model)
        runner = SLORunner(eng, SLOConfig())  # NOT started: nothing drains
        rng = np.random.default_rng(9)
        req = runner.submit(
            rng.integers(1, cfg.vocab_size, 10).tolist(),
            SamplingParams(max_new_tokens=4),
        )
        runner.close()
        assert req.state is RequestState.FINISHED
        assert req.shed and req.shed_reason == "shutdown"

    def test_cancel_retires_dispatched_backlog(self, model):
        """Cancelling a dispatched request before its first token retires
        its cost from the controller backlog (review finding: the leak
        would otherwise pin est_wait high forever)."""
        from radixmesh_tpu.slo.runner import SLORunner

        cfg, _ = model
        eng = make_engine(model)
        runner = SLORunner(eng, SLOConfig())  # NOT started: manual pump
        runner.ctl.observe_service(1000, 1.0)
        rng = np.random.default_rng(11)
        req = runner.submit(
            rng.integers(1, cfg.vocab_size, 10).tolist(),
            SamplingParams(max_new_tokens=4),
        )
        with runner._lock:
            runner._pump()  # dispatch into engine.waiting, admit_time set
        assert req.admit_time > 0
        assert runner.ctl.snapshot()["backlog_tokens"] == 10
        assert runner.cancel(req.rid)
        assert req.cancelled
        assert runner.ctl.snapshot()["backlog_tokens"] == 0
        runner.close()
