"""Bench artifact contracts (no measuring, no jax): the pinned RINGBENCH
schema and the CPU non-evidential marking of BENCH_r{N} emissions —
VERDICT round-5 weak #2/#6 both boil down to 'artifacts must stay
machine-comparable across rounds'."""

import json

import pytest

import bench

pytestmark = pytest.mark.quick


def _run_section(page_size: int) -> dict:
    return {
        "metric": "ring_insert_throughput",
        "value": 2800.0,
        "unit": "inserts/s (ingested+converged, 5 writers, 6 procs)",
        "transport": "native-cpp-tcp",
        "topology": "3 prefill + 2 decode + 1 router (localhost)",
        "inserts_per_writer": 400,
        "key_len_tokens": 256,
        "page_size": page_size,
        "wire_bytes_per_insert": 864 if page_size > 1 else 1584,
        "ingest_s_max": 0.2,
        "converge_s_max": 0.7,
        "oplog_applies_per_s": 14000.0,
        "lap_latency": {"p50_ms": 1.0, "p99_ms": 2.0, "mean_ms": 1.1, "n": 200},
        "route": {"routes_per_s": 12000.0, "p50_ms": 0.08, "p99_ms": 0.14,
                  "mean_ms": 0.08, "n": 5000},
        "wall_s": 16.0,
    }


def _full_report() -> dict:
    paged = _run_section(16)
    token = _run_section(1)
    return {
        "schema_version": bench.RINGBENCH_SCHEMA_VERSION,
        "metric": "ring_insert_throughput",
        "value": paged["value"],
        "unit": paged["unit"],
        "workload": "256-token keys, 400/writer",
        "page_granular": paged,
        "token_granular_baseline": token,
        "bytes_per_insert_ratio": 1.833,
        "inserts_per_s_ratio": 1.3,
        "lap_latency": paged["lap_latency"],
        "round3_wire_bytes_per_insert": bench.RINGBENCH_ROUND3_WIRE_BYTES,
        "vs_round3_wire": 2.421,
    }


class TestRingbenchSchema:
    def test_complete_report_validates(self):
        assert bench.validate_ringbench(_full_report()) == []

    def test_missing_fields_are_named(self):
        report = _full_report()
        del report["lap_latency"]  # the field r04 lacked
        del report["bytes_per_insert_ratio"]  # the field r05 lacked
        del report["page_granular"]["lap_latency"]["p99_ms"]
        missing = bench.validate_ringbench(report)
        assert "lap_latency" in missing
        assert "bytes_per_insert_ratio" in missing
        assert "page_granular.lap_latency.p99_ms" in missing

    def test_run_paired_shape_matches_schema(self):
        """The emitter and the validator agree: a synthetic paired report
        built the way scripts/ringbench.py builds one passes."""
        import importlib.util, os, sys

        spec = importlib.util.spec_from_file_location(
            "_ringbench_schema_check",
            os.path.join(os.path.dirname(bench.__file__) or ".",
                         "scripts", "ringbench.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        # Patch the heavy 6-process run with canned sections; run_paired's
        # assembly logic is what the schema pins.
        sections = iter([_run_section(16), _run_section(1)])
        mod.run = lambda *a, **k: next(sections)
        report = mod.run_paired(400, 200, 5000)
        assert report["schema_version"] == bench.RINGBENCH_SCHEMA_VERSION
        assert "schema_violation" not in report
        assert bench.validate_ringbench(report) == []
        assert report["vs_round3_wire"] == pytest.approx(2092 / 864, abs=1e-3)


class TestNonEvidentialMarking:
    def _emit(self, monkeypatch, tmp_path, capsys, backend: str) -> dict:
        monkeypatch.setattr(bench, "_REPO", str(tmp_path))
        full = {
            "metric": "decode_tokens_per_sec_per_chip",
            "value": 100.0,
            "unit": "tok/s",
            "backend": backend,
            "vs_baseline": 1.5,
        }
        bench._emit(full, {"ok": True, "kernels": {}}, [], [])
        return json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    def test_cpu_rounds_are_flagged(self, monkeypatch, tmp_path, capsys):
        compact = self._emit(monkeypatch, tmp_path, capsys, "cpu")
        assert compact["non_evidential"] is True

    def test_tpu_rounds_are_not(self, monkeypatch, tmp_path, capsys):
        compact = self._emit(monkeypatch, tmp_path, capsys, "tpu")
        assert "non_evidential" not in compact


class TestTraceArtifactSchema:
    """The flight-recorder trace artifact (TRACE_r{N}.json / workload
    trace_path emissions) stays machine-loadable: valid JSON object, a
    traceEvents list, numeric ts/dur, monotonic ts within each lane."""

    def _trace(self) -> dict:
        from radixmesh_tpu.obs.trace_plane import FlightRecorder

        rec = FlightRecorder(capacity=256, sample=1.0)
        ctx = rec.trace("req:1")
        ctx.add("admission_wait", 1.0, 0.01)
        ctx.add("prefill_wave", 1.01, 0.2, wave_rows=2)
        ctx.add("decode_chunk", 1.21, 0.05, k_steps=8)
        ctx.add("publish", 1.26, 0.002)
        rec.event("ring:decode@1", "replication_lag", 1.27, 0.003,
                  origin_rank=0)
        return json.loads(json.dumps(rec.chrome_trace()))

    def test_recorder_export_validates(self):
        assert bench.validate_trace(self._trace()) == []

    def test_violations_are_named(self):
        obj = self._trace()
        xs = [e for e in obj["traceEvents"] if e["ph"] == "X"]
        xs[0]["ts"] = -5.0           # negative timestamp
        xs[1]["dur"] = "fast"        # non-numeric duration
        del xs[2]["tid"]             # no lane
        problems = "\n".join(bench.validate_trace(obj))
        assert "ts invalid" in problems
        assert "dur invalid" in problems
        assert "tid missing" in problems

    def test_ts_regression_within_lane_is_flagged(self):
        obj = self._trace()
        xs = [e for e in obj["traceEvents"] if e["ph"] == "X"]
        same_lane = [e for e in xs if e["tid"] == xs[0]["tid"]]
        assert len(same_lane) >= 2
        same_lane[-1]["ts"] = 0.0  # jump backwards in its lane
        assert any(
            "regresses within tid" in p for p in bench.validate_trace(obj)
        )

    def test_not_an_object_and_missing_events(self):
        assert bench.validate_trace([1, 2]) == ["artifact is not a JSON object"]
        assert bench.validate_trace({"x": 1}) == [
            "traceEvents missing or not a list"
        ]


class TestFleetArtifactSchema:
    """The FLEET artifact (fleet telemetry plane, PR 3) stays machine-
    comparable across rounds: pinned top/section fields, the digest byte
    budget respected, and the one-frame-per-publish piggyback contract."""

    def _report(self) -> dict:
        return {
            "schema_version": bench.FLEET_SCHEMA_VERSION,
            "metric": "fleet_digest_fan_in_p50_s",
            "value": 0.006,
            "unit": "s (one digest round visible on every node incl. router)",
            "workload": "120 inserts over 3 writers + injected divergence "
                        "+ injected stall (inproc ring)",
            "nodes": 4,
            "topology": "2 prefill + 1 decode + 1 router (inproc)",
            "digest_interval_s": 0.1,
            "digest_bytes": 112,
            "digest_byte_budget": 160,
            "fan_in": {"rounds": 5, "p50_s": 0.006, "max_s": 0.009},
            "convergence": {
                "inserts": 120, "writers": 3, "churn_s": 0.05,
                "max_age_during_churn_s": 0.02,
                "quiesce_to_converged_s": 0.1, "converged": True,
                "injected_divergence_detected": True,
                "age_while_diverged_s": 0.16, "healed": True, "heal_s": 0.3,
            },
            "stall_reaction": {
                "injected": True, "detected": True, "reaction_s": 0.05,
                "score_after": 0.0, "threshold": 0.5,
            },
            "health_aware_demotion": True,
            "digests_published": 54,
            "digest_frames_per_publish": 0.98,
            "wall_s": 0.5,
        }

    def test_complete_report_validates(self):
        assert bench.validate_fleet(self._report()) == []

    def test_missing_fields_are_named(self):
        report = self._report()
        del report["health_aware_demotion"]
        del report["convergence"]["heal_s"]
        del report["stall_reaction"]["reaction_s"]
        missing = bench.validate_fleet(report)
        assert "health_aware_demotion" in missing
        assert "convergence.heal_s" in missing
        assert "stall_reaction.reaction_s" in missing

    def test_budget_and_frame_contracts_enforced(self):
        report = self._report()
        report["digest_bytes"] = 900  # over the pinned budget
        report["digest_frames_per_publish"] = 1.4  # piggyback broken
        problems = "\n".join(bench.validate_fleet(report))
        assert "exceeds digest_byte_budget" in problems
        assert "piggyback contract" in problems
        assert bench.validate_fleet([1]) == ["artifact is not a JSON object"]

    def test_emitter_output_matches_schema(self):
        """The workload's real output assembled by build_fleet_report
        passes the validator — emitter and schema cannot drift."""
        res = {
            "nodes": 4,
            "topology": "2 prefill + 1 decode + 1 router (inproc)",
            "digest_interval_s": 0.1,
            "digest_bytes": 112,
            "fan_in": self._report()["fan_in"],
            "convergence": self._report()["convergence"],
            "stall_reaction": self._report()["stall_reaction"],
            "health_aware_demotion": True,
            "digests_published": 54,
            "digest_frames_per_publish": 0.98,
            "wall_s": 0.5,
        }
        report = bench.build_fleet_report(res)
        assert bench.validate_fleet(report) == []
        from radixmesh_tpu.obs.fleet_plane import DIGEST_BYTE_BUDGET

        assert report["digest_byte_budget"] == DIGEST_BYTE_BUDGET


class TestKvflowArtifactSchema:
    """The KVFLOW artifact (async KV-movement plane, PR 4) stays
    machine-comparable across rounds: pinned top/section fields plus the
    two deterministic structural contracts — write-back gathers fused to
    at most one per sweep, and decode progress while a restore is in
    flight strictly above the synchronous path's zero."""

    def _report(self) -> dict:
        return {
            "schema_version": bench.KVFLOW_SCHEMA_VERSION,
            "metric": "kv_restore_overlapped_ttft_ratio",
            "value": 0.94,
            "unit": "overlapped/sync mean TTFT of a host-tier restore burst",
            "workload": "4 host-tier restore requests x 3 interleaved trials",
            "restore": {
                "requests": 4, "repeats": 3,
                "sync_ttft_s": 0.236, "overlapped_ttft_s": 0.222,
                "overlap_ratio": 0.94, "overlap_wins": True,
                "sync_ttft_trials_s": [0.23, 0.22, 0.25],
                "overlapped_ttft_trials_s": [0.19, 0.23, 0.24],
                "sync_restore_ttft_s": 0.7, "overlapped_restore_ttft_s": 0.95,
                "sync_fresh_ttft_s": 0.8, "overlapped_fresh_ttft_s": 0.15,
                "restored_tokens": 3072, "parked_requests": 4,
                "decode_steps_during_restore": 1,
                "sync_decode_steps_during_restore": 0,
                "max_decode_gap_s": 0.29, "sync_max_decode_gap_s": 0.33,
            },
            "writeback": {
                "tokens_written_back": 3072, "sweeps": 1, "gathers": 1,
                "gathers_per_sweep": 1.0, "sync_gathers_per_sweep": 1.0,
                "evict_stall_s": 0.003, "sync_evict_stall_s": 0.04,
            },
            "prefetch": {
                "hints_sent": 8, "hints_joined": 4, "hit_ahead_rate": 1.0,
            },
            "chunk_tokens": 512,
            "ttft_chunk_tokens": 1536,
            "page_size": 4,
            "wall_s": 18.9,
        }

    def test_complete_report_validates(self):
        assert bench.validate_kvflow(self._report()) == []

    def test_missing_fields_are_named(self):
        report = self._report()
        del report["chunk_tokens"]
        del report["restore"]["overlap_wins"]
        del report["prefetch"]["hit_ahead_rate"]
        missing = bench.validate_kvflow(report)
        assert "chunk_tokens" in missing
        assert "restore.overlap_wins" in missing
        assert "prefetch.hit_ahead_rate" in missing

    def test_structural_contracts_enforced(self):
        report = self._report()
        report["writeback"]["gathers_per_sweep"] = 3.0  # unfused
        report["restore"]["decode_steps_during_restore"] = 0  # blocked
        problems = "\n".join(bench.validate_kvflow(report))
        assert "fused-gather contract" in problems
        assert "decode-never-blocks contract" in problems
        assert bench.validate_kvflow([1]) == ["artifact is not a JSON object"]

    def test_build_report_matches_schema(self):
        """build_kvflow_report over a workload-shaped result passes the
        validator — emitter and schema cannot drift."""
        res = self._report()
        for k in ("schema_version", "metric", "value", "unit", "workload"):
            res.pop(k)
        assert bench.validate_kvflow(bench.build_kvflow_report(res)) == []

    def test_checked_in_artifact_validates(self):
        """The round artifact shipped with this PR passes its own
        schema (guards hand-edits and emitter drift alike)."""
        import glob
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(repo, "KVFLOW_r*.json")))
        assert paths, "no KVFLOW artifact checked in"
        with open(paths[-1]) as fh:
            report = json.load(fh)
        assert bench.validate_kvflow(report) == []


class TestChaosArtifactSchema:
    """The CHAOS artifact (self-healing mesh, PR 5) stays machine-
    comparable across rounds: pinned top/section fields plus the three
    structural acceptance gates — converged, within the repair-round
    budget, quiescent after convergence."""

    def _report(self) -> dict:
        return {
            "schema_version": bench.CHAOS_SCHEMA_VERSION,
            "metric": "chaos_heal_converge_s",
            "value": 0.2,
            "unit": "s from fault-window close to ALL replicas pairwise "
                    "fingerprint-equal via anti-entropy repair",
            "workload": "20% seeded frame loss + 10s partition of cp1",
            "nodes": 4,
            "topology": "2 prefill + 1 decode + 1 router (inproc)",
            "round_budget": 8,
            "fault_plan": {
                "seed": 0, "drop_p": 0.2, "drop_window_s": 11.0,
                "partition_s": 10.0, "partitioned_node": "cp1",
                "frames_dropped": 88, "frames_delivered": 3486,
            },
            "served": {
                "attempted": 150, "ok": 150, "ok_rate_during_fault": 1.0,
            },
            "divergence": {
                "detected": True, "peak_diverged_pairs": 3,
                "max_age_s": 10.7,
            },
            "repair": {
                "converged": True, "converge_s": 0.2,
                "max_episode_rounds": 6, "within_round_budget": True,
                "probes_sent": 34, "summaries_sent": 52,
                "keys_pushed": 328, "oplogs_reemitted": 328, "heals": 12,
            },
            "quiescence": {
                "window_s": 2.0, "traffic_before": 86,
                "traffic_after": 86, "quiet": True,
            },
            "drain": {
                "performed": True, "node": "cp2", "drop_p": 0.2,
                "requeued": 6, "requeued_served": 6,
                "attempted_during_drain": 40, "ok_during_drain": 40,
                "zero_failed": True,
                "left_without_failure_detection": True,
                "left_cause_transitions": 1,
                "writeback_tokens": 1150, "writeback_flushed": True,
                "drain_s": 0.6,
            },
            "join": {
                "performed": True, "joiner": "cp2", "donor_rank": 0,
                "partition_active_at_join": True, "partition_s": 1.5,
                "partitioned_node": "cp1",
                "bootstrap_converge_s": 1.8, "bootstrap_rounds": 2,
                "round_budget": 16, "within_round_budget": True,
                "converged_with_donor": True, "withheld_hits": 30,
                "hits_to_bootstrapping": 0, "post_bootstrap_hits": 6,
                "fleet_converged_after_join": True, "join_s": 2.0,
            },
            "crash": {
                "performed": True, "node": "cd0", "drop_p": 0.2,
                "streams": 12, "tokens_per_stream": 24,
                "killed_at_token": 12, "interrupted": 10, "resumed": 10,
                "failed": 0, "prefix_identical": True,
                "replayed_tokens": 280, "replayed_cached_tokens": 268,
                "resurrection_hit_ratio": 0.957, "retries": 10,
                "resurrections": 10, "failover_routes": 10,
                "detection": {
                    "trigger": "hop_timeout", "hop_timeout_s": 0.4,
                    "detect_s": 0.4,
                },
                "budget": {
                    "deadline_s": 20.0, "max_overrun_s": 0.0,
                    "max_backoff_s": 0.06, "within_one_backoff": True,
                },
                "hedge": {
                    "fired": True, "winner": "cp1",
                    "first_writer_wins": True, "loser_cancelled": True,
                },
                "crash_s": 9.2,
            },
            "rebalance": {
                "performed": True, "skew_before": 20.3, "skew_after": 14.6,
                "skew_dropped": True, "moves": 4,
                "max_moves_per_round": 4, "moves_bounded": True,
                "boosted_shards": [19, 42, 37, 58], "hot_shard": 19,
                "attempted_mid_move": 175, "ok_mid_move": 175,
                "failed_mid_move": 0, "overrides_version": 1,
                "overrides_converged": True, "handoff_entries": 8,
                "requests_wave1": 155, "rebalance_s": 6.0,
            },
            "router_kill": {
                "performed": True, "routers": 2, "killed": "cr0",
                "survivor": "cr1", "streams": 10, "inflight_at_kill": 10,
                "completed": 10, "failed": 0, "failovers": 1, "hedges": 1,
                "survivor_served": True, "router_kill_s": 0.4,
            },
            "wall_s": 14.7,
        }

    def test_complete_report_validates(self):
        assert bench.validate_chaos(self._report()) == []

    def test_missing_fields_are_named(self):
        report = self._report()
        del report["round_budget"]
        del report["repair"]["converge_s"]
        del report["quiescence"]["quiet"]
        del report["drain"]["writeback_tokens"]
        del report["join"]["bootstrap_rounds"]
        del report["crash"]["resurrection_hit_ratio"]
        missing = bench.validate_chaos(report)
        assert "round_budget" in missing
        assert "repair.converge_s" in missing
        assert "quiescence.quiet" in missing
        assert "drain.writeback_tokens" in missing
        assert "join.bootstrap_rounds" in missing
        assert "crash.resurrection_hit_ratio" in missing

    def test_acceptance_gates_enforced(self):
        report = self._report()
        report["repair"]["converged"] = False
        report["repair"]["within_round_budget"] = False
        report["divergence"]["detected"] = False
        report["quiescence"]["quiet"] = False
        problems = "\n".join(bench.validate_chaos(report))
        assert "never healed" in problems
        assert "exceeded round_budget" in problems
        assert "injected nothing" in problems
        assert "kept flowing" in problems
        assert bench.validate_chaos(7) == ["artifact is not a JSON object"]

    def test_lifecycle_gates_enforced(self):
        """The PR 6 membership gates: a drain that failed requests or
        tripped failure detection, or a join the router kept hit-routing
        to (or that never converged), must be named violations."""
        report = self._report()
        report["drain"]["zero_failed"] = False
        report["drain"]["left_without_failure_detection"] = False
        report["drain"]["writeback_flushed"] = False
        report["drain"]["requeued_served"] = 3
        report["join"]["converged_with_donor"] = False
        report["join"]["within_round_budget"] = False
        report["join"]["hits_to_bootstrapping"] = 4
        report["join"]["withheld_hits"] = 0
        problems = "\n".join(bench.validate_chaos(report))
        assert "requests failed during the graceful drain" in problems
        assert "requeued but not all served" in problems
        assert "tripped failure detection" in problems
        assert "not written back" in problems
        assert "never converged with its donor" in problems
        assert "over the budget" in problems
        assert "routed cache hits to a BOOTSTRAPPING node" in problems
        assert "never withheld a hit" in problems

    def test_crash_gates_enforced(self):
        """The PR 7 request-recovery gates: a kill that lost requests,
        a resume that corrupted the delivered prefix, a replay the cache
        didn't serve, a budget overrun past one backoff, or a hedge that
        broke first-writer-wins must all be named violations."""
        report = self._report()
        report["crash"]["failed"] = 2
        report["crash"]["resumed"] = 8
        report["crash"]["prefix_identical"] = False
        report["crash"]["resurrection_hit_ratio"] = 0.5
        report["crash"]["budget"]["within_one_backoff"] = False
        report["crash"]["hedge"]["first_writer_wins"] = False
        report["crash"]["hedge"]["loser_cancelled"] = False
        problems = "\n".join(bench.validate_chaos(report))
        assert "LOST to the unclean kill" in problems
        assert "not all resurrected" in problems
        assert "prefix not byte-identical" in problems
        assert "below 0.8" in problems
        assert "more than one retry backoff" in problems
        assert "first successful writer did not win" in problems
        assert "loser was not cancelled" in problems

    def test_crash_must_interrupt_something(self):
        """A kill that interrupted zero live streams proves nothing —
        the gate refuses vacuous passes."""
        report = self._report()
        report["crash"]["interrupted"] = 0
        report["crash"]["resumed"] = 0
        problems = "\n".join(bench.validate_chaos(report))
        assert "interrupted zero live streams" in problems

    def test_v1_artifact_without_lifecycle_sections_stays_valid(self):
        """CHAOS_r06 predates the join/drain sections: v1 artifacts must
        keep validating (version bumps add, never break)."""
        report = self._report()
        del report["drain"]
        del report["join"]
        del report["crash"]
        report["schema_version"] = 1
        assert bench.validate_chaos(report) == []

    def test_v2_artifact_without_crash_section_stays_valid(self):
        """CHAOS_r07 predates the crash section: v2 artifacts must keep
        validating with the join/drain sections but no crash."""
        report = self._report()
        del report["crash"]
        del report["rebalance"]
        del report["router_kill"]
        report["schema_version"] = 2
        assert bench.validate_chaos(report) == []

    def test_v3_artifact_without_robustness_sections_stays_valid(self):
        """CHAOS_r08 predates the rebalance/router_kill sections (PR 14):
        v3 artifacts must keep validating without them."""
        report = self._report()
        del report["rebalance"]
        del report["router_kill"]
        report["schema_version"] = 3
        assert bench.validate_chaos(report) == []

    def test_skipped_phase_is_schema_valid_but_gate_exempt(self):
        report = self._report()
        report["drain"] = {"performed": False}
        report["join"] = {"performed": False}
        report["crash"] = {"performed": False}
        report["rebalance"] = {"performed": False}
        report["router_kill"] = {"performed": False}
        assert bench.validate_chaos(report) == []

    def test_rebalance_gates_enforced(self):
        """The PR 14 robustness-loop gates: a storm whose skew did not
        strictly drop, requests failed mid-move, unbounded or zero
        movement, or a fleet that never converged on the override
        version must all be named violations."""
        report = self._report()
        report["rebalance"]["skew_after"] = report["rebalance"][
            "skew_before"
        ]
        report["rebalance"]["failed_mid_move"] = 3
        report["rebalance"]["moves"] = 0
        report["rebalance"]["moves_bounded"] = False
        report["rebalance"]["overrides_converged"] = False
        problems = "\n".join(bench.validate_chaos(report))
        assert "did not strictly drop" in problems
        assert "failed mid-move" in problems
        assert "zero adopted moves" in problems
        assert "exceeded the per-round bound" in problems
        assert "never converged on the decider's override version" in problems

    def test_router_kill_gates_enforced(self):
        report = self._report()
        report["router_kill"]["routers"] = 1
        report["router_kill"]["failed"] = 1
        report["router_kill"]["completed"] = 8
        report["router_kill"]["inflight_at_kill"] = 0
        report["router_kill"]["failovers"] = 0
        report["router_kill"]["survivor_served"] = False
        problems = "\n".join(bench.validate_chaos(report))
        assert "needs N >= 2" in problems
        assert "LOST to the router kill" in problems
        assert "did not all complete" in problems
        assert "interrupted zero in-flight streams" in problems
        assert "never failed over" in problems
        assert "served no post-kill routes" in problems

    def test_build_report_matches_schema(self):
        res = {
            k: self._report()[k]
            for k in (
                "nodes", "topology", "round_budget", "fault_plan", "served",
                "divergence", "repair", "quiescence", "drain", "join",
                "crash", "rebalance", "router_kill", "wall_s",
            )
        }
        report = bench.build_chaos_report(res)
        assert bench.validate_chaos(report) == []
        assert report["value"] == res["repair"]["converge_s"]

    def test_checked_in_artifact_validates(self):
        import glob
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(repo, "CHAOS_r*.json")))
        assert paths, "no CHAOS artifact checked in"
        with open(paths[-1]) as fh:
            report = json.load(fh)
        assert bench.validate_chaos(report) == []


class TestRingscaleArtifactSchema:
    """RINGSCALE v2 (scripts/ringscale.py + prefix-ownership sharding,
    cache/sharding.py): per-row rf/mode fields, the bytes-per-insert
    FLATNESS gate for sharded rows, the owner-propagation gate, and v1
    (pre-sharding, full-replica-only) artifacts staying valid."""

    @staticmethod
    def _row(n, rf, mode="sim", bytes_=None, p99=None):
        return {
            "n_nodes": n,
            "topology": "ring",
            "rf": rf,
            "mode": mode,
            "hop_delay_ms": 1.0,
            "frame_bytes": 252,
            "frames_per_insert": rf if rf else n,
            "measured_frames_per_insert": float(rf if rf else n),
            "ring_bytes_per_insert": (
                bytes_ if bytes_ is not None else 252 * (rf if rf else n)
            ),
            "prop_p50_ms": p99 if p99 is not None else 1.0,
            "prop_p99_ms": p99 if p99 is not None else 1.0,
        }

    def _report(self, rows):
        return {
            "schema_version": 2,
            "metric": "ring_scale_sweep",
            "mode": "mixed:live+sim",
            "sizes": sorted({r["n_nodes"] for r in rows}),
            "hop_delays_ms": [1.0],
            "rfs": sorted({r["rf"] for r in rows}),
            "results": rows,
            "bytes_per_insert_growth": {},
        }

    def test_complete_report_validates(self):
        rows = [
            self._row(12, 0, p99=11.0),
            self._row(200, 0, p99=199.0),
            self._row(12, 3, p99=1.0),
            self._row(200, 3, p99=1.0),
        ]
        assert bench.validate_ringscale(self._report(rows)) == []

    def test_missing_row_fields_are_named(self):
        rows = [self._row(12, 3)]
        del rows[0]["ring_bytes_per_insert"]
        problems = bench.validate_ringscale(self._report(rows))
        assert "results[0].ring_bytes_per_insert" in problems

    def test_flatness_gate_enforced(self):
        """Sharded bytes-per-insert growing with N is exactly the O(N)
        wall the plane exists to break — the gate must catch it."""
        rows = [
            self._row(12, 3, bytes_=756),
            self._row(200, 3, bytes_=7560),  # 10x growth: the wall is back
        ]
        problems = bench.validate_ringscale(self._report(rows))
        assert any("flatness" in p for p in problems), problems
        # Within 1.5x passes.
        rows = [self._row(12, 3, bytes_=700), self._row(200, 3, bytes_=756)]
        assert bench.validate_ringscale(self._report(rows)) == []

    def test_propagation_gate_enforced(self):
        """Owner-propagation p99 must not exceed the full-replica ring
        at the smallest size (same delay + mode)."""
        rows = [
            self._row(12, 0, mode="threads+tcp-py", p99=10.0),
            self._row(12, 3, mode="threads+tcp-py", p99=50.0),
        ]
        problems = bench.validate_ringscale(self._report(rows))
        assert any("propagation" in p for p in problems), problems
        # Sim rows are not compared against live rows.
        rows = [
            self._row(12, 0, mode="threads+tcp-py", p99=10.0),
            self._row(200, 3, mode="sim", p99=50.0),
        ]
        assert bench.validate_ringscale(self._report(rows)) == []

    def test_v1_artifact_stays_valid(self):
        """Pre-sharding artifacts (no schema_version; full-replica rows
        without rf/mode fields) keep validating as-is."""
        v1 = {
            "metric": "ring_scale_sweep",
            "mode": "procs+native",
            "sizes": [12, 25],
            "results": [
                {"n_nodes": 12, "topology": "ring",
                 "ring_bytes_per_insert": 3024},
            ],
        }
        assert bench.validate_ringscale(v1) == []
        assert bench.validate_ringscale({"metric": "other"}) != []

    def test_checked_in_artifacts_validate(self):
        import glob
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(repo, "RINGSCALE_r*.json")))
        assert paths, "no RINGSCALE artifact checked in"
        for path in paths:
            with open(path) as fh:
                report = json.load(fh)
            assert bench.validate_ringscale(report) == [], path
        # The newest artifact must be current-schema and actually
        # demonstrate the flat sharded curve at the 200-node ceiling;
        # from v3 on it must also carry the owner-propagation-under-
        # overrides row (the PR 14 deferral, measured in PR 15).
        with open(paths[-1]) as fh:
            newest = json.load(fh)
        assert newest.get("schema_version") >= 2
        sharded = [
            r for r in newest["results"] if int(r.get("rf", 0)) > 0
        ]
        assert any(r["n_nodes"] >= 200 for r in sharded)
        if newest.get("schema_version") >= 3:
            ov = [
                r for r in newest["results"] if r.get("overrides_active")
            ]
            assert ov and all(int(r.get("rf", 0)) > 0 for r in ov)
            assert any(r["n_nodes"] >= 200 for r in ov)


class TestObsArtifactSchema:
    """OBS v1 (PR 9, mesh-wide observability): the stitched-trace gate
    (interrupted request on >= OBS_MIN_NODE_TRACKS node tracks under one
    trace id, replication edges visible, zero lost streams), the heat
    gate (zipf hot shard detected with the correct owner set, skew above
    the floor), the step-attribution gate (per-wave MFU + pad fraction
    for prefill AND decode), and the wire gate (traceless frames
    bit-for-bit pre-PR-9)."""

    def _report(self) -> dict:
        return {
            "schema_version": bench.OBS_SCHEMA_VERSION,
            "metric": "obs_stitched_node_tracks",
            "value": 6,
            "unit": "node tracks under a single 64-bit trace id",
            "workload": "traced crash drill + zipf heat + tiny-engine burst",
            "nodes": 7,
            "topology": "4 prefill + 2 decode + 1 router (inproc)",
            "replication_factor": 3,
            "stitch": {
                "performed": True, "node": "od0", "streams": 8,
                "tokens_per_stream": 20, "interrupted": 6, "resumed": 6,
                "failed": 0, "trace_id": "0x3da6417a0df7ba6d",
                "node_tracks": 6,
                "nodes_on_track": [
                    "decode@4", "decode@5", "obs-edge",
                    "prefill@0", "prefill@2", "prefill@3",
                ],
                "replication_edges": 37, "publish_edges": 20,
                "span_count": 2544, "stitched_events": 2561,
            },
            "heat": {
                "performed": True, "inserts": 394, "distinct_keys": 64,
                "zipf_alpha": 1.4, "skew_score": 16.3,
                "hot_shard": 7, "expected_hot_shard": 7,
                "hot_owners": [0, 1, 2, 4, 5],
                "expected_hot_owners": [0, 1, 2, 4, 5],
                "owner_set_correct": True, "reporters": 6,
            },
            "steps": {
                "performed": True, "n_params": 426624, "peak_tflops": 1.0,
                "prefill": {
                    "waves": 3, "real_tokens": 19, "padded_tokens": 32,
                    "mfu": 1.0e-05, "pad_fraction": 0.40625,
                },
                "decode": {
                    "waves": 30, "real_tokens": 45, "padded_tokens": 60,
                    "mfu": 1.9e-05, "pad_fraction": 0.25,
                },
            },
            "wire": {
                "rf0_traceless_unchanged": True,
                "trace_trailer_roundtrip": True,
                "trailer_bytes": 8,
            },
            "wall_s": 10.7,
        }

    def test_complete_report_validates(self):
        assert bench.validate_obs(self._report()) == []

    def test_missing_fields_are_named(self):
        report = self._report()
        del report["replication_factor"]
        del report["stitch"]["trace_id"]
        del report["heat"]["skew_score"]
        del report["steps"]["prefill"]["mfu"]
        del report["wire"]["trailer_bytes"]
        missing = bench.validate_obs(report)
        assert "replication_factor" in missing
        assert "stitch.trace_id" in missing
        assert any("skew_score" in m for m in missing)
        assert "steps.prefill.mfu" in missing
        assert "wire.trailer_bytes" in missing
        assert bench.validate_obs(7) == ["artifact is not a JSON object"]

    def test_stitch_gates_enforced(self):
        report = self._report()
        report["stitch"]["node_tracks"] = bench.OBS_MIN_NODE_TRACKS - 1
        report["stitch"]["failed"] = 2
        report["stitch"]["resumed"] = 3
        report["stitch"]["replication_edges"] = 0
        problems = "\n".join(bench.validate_obs(report))
        assert "did not stitch" in problems
        assert "LOST" in problems
        assert "not all resurrected" in problems
        assert "no replication edges" in problems

    def test_heat_gates_enforced(self):
        report = self._report()
        report["heat"]["skew_score"] = bench.OBS_MIN_SKEW_SCORE - 0.5
        report["heat"]["hot_shard"] = 9
        report["heat"]["owner_set_correct"] = False
        report["heat"]["reporters"] = 0
        problems = "\n".join(bench.validate_obs(report))
        assert "skew score" in problems
        assert "ground truth" in problems
        assert "owner set was not correctly named" in problems
        assert "zero heat reporters" in problems

    def test_step_and_wire_gates_enforced(self):
        report = self._report()
        report["steps"]["decode"]["waves"] = 0
        report["steps"]["decode"]["mfu"] = 0.0
        report["steps"]["prefill"]["pad_fraction"] = 1.5
        report["wire"]["rf0_traceless_unchanged"] = False
        problems = "\n".join(bench.validate_obs(report))
        assert "zero decode waves" in problems
        assert "decode MFU" in problems
        assert "pad fraction" in problems
        assert "bit-for-bit" in problems

    def test_skipped_legs_are_schema_valid_but_gate_exempt(self):
        report = self._report()
        report["stitch"] = {"performed": False}
        report["heat"] = {"performed": False}
        report["steps"] = {"performed": False}
        assert bench.validate_obs(report) == []

    def test_build_report_matches_schema(self):
        res = {
            k: self._report()[k]
            for k in (
                "nodes", "topology", "replication_factor", "stitch",
                "heat", "steps", "wire", "wall_s",
            )
        }
        report = bench.build_obs_report(res)
        assert bench.validate_obs(report) == []
        assert report["value"] == res["stitch"]["node_tracks"]

    def test_checked_in_artifact_validates_and_gates_green(self):
        import glob
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(repo, "OBS_r*.json")))
        assert paths, "no OBS artifact checked in"
        with open(paths[-1]) as fh:
            report = json.load(fh)
        assert bench.validate_obs(report) == []
        assert "schema_violation" not in report
        # The acceptance headline numbers really are in the artifact.
        assert report["stitch"]["node_tracks"] >= bench.OBS_MIN_NODE_TRACKS
        assert report["heat"]["skew_score"] >= bench.OBS_MIN_SKEW_SCORE
        assert report["steps"]["performed"] is True


class TestAnalysisArtifactSchema:
    """ANALYSIS v2 (PR 11, meshcheck concurrency plane): the artifact
    gates on ZERO unsuppressed findings over the tree, every default
    checker present (now including thread-roots/guarded-by/protocol,
    each with per-checker control counts), every positive-control
    fixture tripped, a justification on every suppression, and a
    non-empty derived thread map. v1 artifacts stay valid against the
    v1 field/checker sets."""

    def _report(self, version: int | None = None) -> dict:
        version = bench.ANALYSIS_SCHEMA_VERSION if version is None else version
        checker = {
            "id": "lock-order",
            "description": "x",
            "raw_findings": 0,
            "kept_findings": 0,
            "suppressed": 0,
        }
        ids = (
            bench.ANALYSIS_CHECKER_IDS if version >= 2
            else bench.ANALYSIS_CHECKER_IDS_V1
        )
        if version >= 2:
            checker.update(controls=1, controls_tripped=1)
        report = {
            "schema_version": version,
            "metric": "unsuppressed_findings",
            "value": 0,
            "package": "radixmesh_tpu",
            "files_indexed": 80,
            "checkers": [dict(checker, id=cid) for cid in ids],
            "findings": [],
            "suppressions": [
                {
                    "file": "workload.py", "line": 19, "scope": "file",
                    "invariants": ["sleep-audit"],
                    "justification": "generators pace by wall clock",
                    "used": True,
                },
            ],
            "positive_controls": [
                {
                    "fixture": "lock_cycle",
                    "invariant": "lock-order-cycle",
                    "file": "engine/engine.py", "line": 19,
                    "tripped": True,
                },
            ],
            "clean": True,
        }
        if version >= 2:
            report["thread_roots"] = {
                "count": 2,
                "roots": [
                    {
                        "name": "mesh-sender",
                        "target": "cache/mesh_cache.py:MeshCache._sender",
                        "file": "cache/mesh_cache.py", "line": 624,
                        "multi": False, "kind": "spawn",
                    },
                    {
                        "name": "wire-receive",
                        "target": "cache/mesh_cache.py:MeshCache.oplog_received",
                        "file": "cache/mesh_cache.py", "line": 905,
                        "multi": True, "kind": "declared",
                    },
                ],
            }
        return report

    def test_valid_report_passes(self):
        assert bench.validate_analysis(self._report()) == []

    def test_v1_report_stays_valid(self):
        """A PR 10 artifact (no thread map, five checkers, no control
        counts) still validates — old rounds stay comparable."""
        assert bench.validate_analysis(self._report(version=1)) == []

    def test_v1_checked_in_artifact_still_validates(self):
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        path = os.path.join(repo, "ANALYSIS_r10.json")
        with open(path) as fh:
            assert bench.validate_analysis(json.load(fh)) == []

    def test_v2_requires_concurrency_checkers(self):
        report = self._report()
        report["checkers"] = [
            c for c in report["checkers"] if c["id"] != "guarded-by"
        ]
        problems = "\n".join(bench.validate_analysis(report))
        assert "guarded-by" in problems and "checked less" in problems

    def test_v2_requires_thread_map(self):
        report = self._report()
        del report["thread_roots"]
        assert any(
            "thread_roots" in p for p in bench.validate_analysis(report)
        )
        report = self._report()
        report["thread_roots"] = {"count": 0, "roots": []}
        problems = "\n".join(bench.validate_analysis(report))
        assert "checked nothing" in problems

    def test_v2_thread_map_entries_are_schema_complete(self):
        report = self._report()
        del report["thread_roots"]["roots"][0]["multi"]
        problems = "\n".join(bench.validate_analysis(report))
        assert "mesh-sender" in problems and ".multi" in problems
        report = self._report()
        report["thread_roots"]["count"] = 5
        problems = "\n".join(bench.validate_analysis(report))
        assert "disagrees" in problems

    def test_v2_requires_control_counts(self):
        report = self._report()
        del report["checkers"][0]["controls_tripped"]
        problems = "\n".join(bench.validate_analysis(report))
        assert "controls_tripped" in problems

    def test_missing_fields_reported(self):
        report = self._report()
        del report["positive_controls"]
        del report["files_indexed"]
        missing = bench.validate_analysis(report)
        assert any("files_indexed" in p for p in missing)
        assert any("positive_controls" in p for p in missing)

    def test_non_dict_rejected(self):
        assert bench.validate_analysis([1]) == ["artifact is not a JSON object"]

    def test_findings_fail_the_gate(self):
        report = self._report()
        report["findings"] = [
            {"file": "cache/mesh_cache.py", "line": 7,
             "invariant": "send-seam", "message": "raw send"},
        ]
        report["clean"] = False
        problems = "\n".join(bench.validate_analysis(report))
        assert "unsuppressed finding" in problems

    def test_clean_flag_must_agree(self):
        report = self._report()
        report["clean"] = False
        problems = "\n".join(bench.validate_analysis(report))
        assert "clean flag disagrees" in problems

    def test_untripped_control_fails(self):
        report = self._report()
        report["positive_controls"][0]["tripped"] = False
        problems = "\n".join(bench.validate_analysis(report))
        assert "NOT tripped" in problems and "went blind" in problems

    def test_empty_controls_fail(self):
        report = self._report()
        report["positive_controls"] = []
        problems = "\n".join(bench.validate_analysis(report))
        assert "proves nothing" in problems

    def test_missing_checker_fails(self):
        report = self._report()
        report["checkers"] = report["checkers"][1:]
        problems = "\n".join(bench.validate_analysis(report))
        assert "lock-order" in problems and "checked less" in problems

    def test_unjustified_suppression_fails(self):
        report = self._report()
        report["suppressions"][0]["justification"] = "  "
        problems = "\n".join(bench.validate_analysis(report))
        assert "silencing" in problems

    def test_checked_in_artifact_validates_and_is_clean(self):
        import glob
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(repo, "ANALYSIS_r*.json")))
        assert paths, "no ANALYSIS artifact checked in"
        with open(paths[-1]) as fh:
            report = json.load(fh)
        assert bench.validate_analysis(report) == [], paths[-1]
        assert "schema_violation" not in report
        assert report["clean"] is True and report["value"] == 0
        # Every checker of the default plane ran over a real tree.
        assert report["files_indexed"] >= 70
        # All controls tripped, and they cover every checker family.
        fixtures = {c["fixture"] for c in report["positive_controls"]}
        assert {
            "lock_cycle", "single_writer_alias", "hotpath_sleep",
            "wire_unregistered", "metrics_vocab", "send_seam",
            "suppression_grammar",
            # v2 (PR 11): the concurrency plane's controls.
            "guarded_race", "thread_escape", "protocol_drift",
        } <= fixtures
        # v2: the thread map shipped with the verdict it parameterized.
        assert report["thread_roots"]["count"] >= 15
        root_names = {r["name"] for r in report["thread_roots"]["roots"]}
        assert {"mesh-sender", "wire-receive", "kv-transfer"} <= root_names


class TestDoctorArtifactSchema:
    """DOCTOR v1 (PR 12, the diagnosis plane): zero findings on the
    healthy phase with EVERY rule running, each seeded pathology named
    with evidence matching the seeded ground truth, the phase
    decomposition summing to e2e within epsilon on every audited
    request, and the benchdiff sentinel's three-way self-check."""

    def _pathology(self, rule: str, evidence: dict) -> dict:
        return {
            "performed": True,
            "rule": rule,
            "detected": True,
            "evidence_correct": True,
            "score": 0.9,
            "summary": f"{rule} fired",
            "evidence": evidence,
            "expected": dict(evidence),
        }

    def _report(self) -> dict:
        from radixmesh_tpu.obs.doctor import RULES

        return {
            "schema_version": bench.DOCTOR_SCHEMA_VERSION,
            "metric": "doctor_pathologies_named",
            "value": 3,
            "unit": "of 3 seeded pathologies named with correct evidence",
            "workload": "healthy + heat storm + convoy + throttled restore",
            "nodes": 7,
            "topology": "4 prefill + 2 decode + 1 router (inproc) + engine",
            "replication_factor": 3,
            "healthy": {
                "performed": True,
                "findings": [],
                "rules_checked": list(RULES),
                "inputs": {"mesh": True, "engine": True, "slo": True,
                           "attribution": True},
                "audited_requests": 6,
            },
            "pathologies": {
                "hot_shard": self._pathology("hot_shard", {
                    "skew_score": 19.5, "shard": 7,
                    "owners": [0, 1, 2, 4, 5], "reporters": 6,
                }),
                "prefill_convoy": self._pathology("prefill_convoy", {
                    "shape": "p2048", "prefill_share": 0.95,
                    "mean_e2e_s": 0.2, "fleet_mean_e2e_s": 0.04,
                    "requests": 3,
                }),
                "restore_park_stall": self._pathology("restore_park_stall", {
                    "lane": "restore", "parked": 3, "restores_queued": 4,
                    "park_p99_s": 0.0001, "park_share": 0.0,
                }),
            },
            "attribution": {
                "audited": 18, "refused": 0, "max_sum_error_s": 0.0,
                "epsilon_s": bench.DOCTOR_SUM_EPSILON_S, "sums_ok": True,
                "phases": {},
            },
            "benchdiff": {
                "identical_clean": True, "regression_flagged": True,
                "mismatch_detected": True,
            },
            "wall_s": 12.0,
        }

    def test_complete_report_validates(self):
        assert bench.validate_doctor(self._report()) == []

    def test_missing_fields_are_named(self):
        report = self._report()
        del report["pathologies"]["hot_shard"]
        del report["attribution"]["sums_ok"]
        del report["healthy"]["audited_requests"]
        problems = bench.validate_doctor(report)
        assert any("pathologies.hot_shard" in p for p in problems)
        assert any("attribution.sums_ok" in p for p in problems)
        assert any("healthy.audited_requests" in p for p in problems)

    def test_healthy_findings_fail_the_gate(self):
        report = self._report()
        report["healthy"]["findings"] = [{"rule": "hot_shard"}]
        problems = "\n".join(bench.validate_doctor(report))
        assert "cries wolf" in problems

    def test_all_rules_must_have_run_on_healthy(self):
        report = self._report()
        report["healthy"]["rules_checked"] = ["hot_shard"]
        problems = "\n".join(bench.validate_doctor(report))
        assert "never ran" in problems

    def test_undetected_pathology_fails(self):
        report = self._report()
        report["pathologies"]["prefill_convoy"]["detected"] = False
        problems = "\n".join(bench.validate_doctor(report))
        assert "NOT detected" in problems

    def test_wrong_evidence_fails(self):
        report = self._report()
        report["pathologies"]["hot_shard"]["evidence_correct"] = False
        problems = "\n".join(bench.validate_doctor(report))
        assert "ground truth" in problems

    def test_evidence_must_carry_pinned_fields(self):
        report = self._report()
        del report["pathologies"]["hot_shard"]["evidence"]["owners"]
        problems = "\n".join(bench.validate_doctor(report))
        assert "pinned" in problems and "owners" in problems

    def test_sum_epsilon_gate_enforced(self):
        report = self._report()
        report["attribution"]["max_sum_error_s"] = 0.01
        report["attribution"]["sums_ok"] = False
        problems = "\n".join(bench.validate_doctor(report))
        assert "sum to e2e" in problems

    def test_refusals_fail_the_acceptance_run(self):
        report = self._report()
        report["attribution"]["refused"] = 2
        problems = "\n".join(bench.validate_doctor(report))
        assert "refusal" in problems

    def test_benchdiff_sentinel_gates(self):
        for key in ("identical_clean", "regression_flagged",
                    "mismatch_detected"):
            report = self._report()
            report["benchdiff"][key] = False
            assert bench.validate_doctor(report), key

    def test_skipped_sections_are_schema_valid_but_gate_exempt(self):
        report = self._report()
        report["healthy"] = {"performed": False}
        report["pathologies"]["hot_shard"] = {"performed": False}
        assert bench.validate_doctor(report) == []

    def test_build_report_matches_schema(self):
        core = {k: v for k, v in self._report().items()
                if k not in ("schema_version", "metric", "value", "unit",
                             "workload")}
        report = bench.build_doctor_report(core)
        assert bench.validate_doctor(report) == []
        assert report["value"] == 3

    def test_checked_in_artifact_validates_and_gates_green(self):
        import glob
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(repo, "DOCTOR_r*.json")))
        assert paths, "no DOCTOR artifact checked in"
        with open(paths[-1]) as fh:
            report = json.load(fh)
        assert bench.validate_doctor(report) == [], paths[-1]
        assert "schema_violation" not in report
        assert report["value"] == len(bench.DOCTOR_PATHOLOGIES)
        assert report["healthy"]["findings"] == []
        # The hot shard's owner set was named AND matched ground truth.
        hot = report["pathologies"]["hot_shard"]
        assert hot["evidence"]["owners"] == hot["expected"]["owners"]
        assert report["attribution"]["max_sum_error_s"] <= (
            report["attribution"]["epsilon_s"]
        )


class TestCompareRounds:
    """The regression sentinel (bench.compare_rounds): per-kind pinned
    metric directions, additive-version tolerance, and the pinned
    status vocabulary the CLI's exit codes map onto."""

    def _chaos(self, **over) -> dict:
        base = {
            "metric": "chaos_heal_converge_s",
            "schema_version": bench.CHAOS_SCHEMA_VERSION,
            "value": 0.4,
            "crash": {"resurrection_hit_ratio": 0.95},
            "repair": {"converge_s": 0.4},
        }
        base.update(over)
        return base

    def test_identical_pair_is_clean(self):
        r = bench.compare_rounds(self._chaos(), self._chaos(), kind="CHAOS")
        assert r["status"] == "clean"
        assert r["regressions"] == []

    def test_adverse_move_past_threshold_flags(self):
        worse = self._chaos(value=1.8, repair={"converge_s": 1.8})
        r = bench.compare_rounds(self._chaos(), worse, kind="CHAOS")
        assert r["status"] == "regression"
        assert "repair.converge_s" in r["regressions"]

    def test_adverse_move_inside_threshold_is_noise(self):
        slightly = self._chaos(value=0.45, repair={"converge_s": 0.45})
        r = bench.compare_rounds(self._chaos(), slightly, kind="CHAOS")
        assert r["status"] == "clean"

    def test_improvement_direction_respected(self):
        better = self._chaos(
            value=0.1, repair={"converge_s": 0.1},
            crash={"resurrection_hit_ratio": 0.99},
        )
        r = bench.compare_rounds(self._chaos(), better, kind="CHAOS")
        assert r["status"] == "clean"
        assert "repair.converge_s" in r["improvements"]

    def test_higher_better_metric_drop_flags(self):
        worse = self._chaos(crash={"resurrection_hit_ratio": 0.5})
        r = bench.compare_rounds(self._chaos(), worse, kind="CHAOS")
        assert "crash.resurrection_hit_ratio" in r["regressions"]

    def test_kind_mismatch_refuses(self):
        obs = {"metric": "obs_stitched_node_tracks", "schema_version": 1,
               "value": 6}
        r = bench.compare_rounds(self._chaos(), obs)
        assert r["status"] == "schema_mismatch"

    def test_unrecognized_kind_refuses(self):
        r = bench.compare_rounds({"metric": "nope"}, {"metric": "nope"})
        assert r["status"] == "schema_mismatch"

    def test_kind_detected_from_filename(self):
        assert bench.artifact_kind({}, "CHAOS_r08.json") == "CHAOS"
        assert bench.artifact_kind({}, "/a/b/BENCH_FULL_r05.json") == (
            "BENCH_FULL"
        )
        assert bench.artifact_kind({}, "notes.json") is None

    def test_version_bump_skips_one_sided_fields(self):
        old = self._chaos(schema_version=2)
        del old["crash"]  # the section arrived with v3
        r = bench.compare_rounds(old, self._chaos(), kind="CHAOS")
        assert r["status"] == "clean"
        assert "crash.resurrection_hit_ratio" in r["skipped"]
        assert r["version_change"] == {
            "old": 2, "new": bench.CHAOS_SCHEMA_VERSION,
        }

    def test_same_version_one_sided_field_refuses(self):
        old = self._chaos()
        del old["crash"]
        r = bench.compare_rounds(old, self._chaos(), kind="CHAOS")
        assert r["status"] == "schema_mismatch"

    def test_threshold_scale_zero_flags_any_adverse_move(self):
        slightly = self._chaos(value=0.41, repair={"converge_s": 0.41})
        r = bench.compare_rounds(
            self._chaos(), slightly, kind="CHAOS", threshold_scale=0.0
        )
        assert r["status"] == "regression"

    def test_unguarded_numeric_moves_are_informational(self):
        moved = self._chaos()
        moved["wall_s"] = 99.0
        old = self._chaos()
        old["wall_s"] = 10.0
        r = bench.compare_rounds(old, moved, kind="CHAOS")
        assert r["status"] == "clean"
        assert any(c["path"] == "wall_s" for c in r["info_changes"])

    def test_every_rule_path_resolves_in_checked_in_artifacts(self):
        """Rot guard: each kind's pinned paths must exist in the LATEST
        checked-in artifact of that kind (else the sentinel silently
        guards nothing)."""
        import glob
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        for kind, rules in bench.COMPARE_RULES.items():
            paths = sorted(glob.glob(os.path.join(repo, f"{kind}_r*.json")))
            if not paths or not rules:
                continue
            with open(paths[-1]) as fh:
                artifact = json.load(fh)
            for path, _, _ in rules:
                v = bench._dotted_get(artifact, path)
                assert isinstance(v, (int, float)), (
                    f"{kind}: pinned path {path!r} does not resolve to a "
                    f"number in {os.path.basename(paths[-1])} (got {v!r})"
                )

    def test_selfcheck_is_green(self):
        check = bench.benchdiff_selfcheck()
        assert check["identical_clean"] is True
        assert check["regression_flagged"] is True
        assert check["mismatch_detected"] is True


class TestBenchdiffCLI:
    """scripts/benchdiff.py pinned exit codes: 0 clean / 1 regression /
    2 schema mismatch — the contract CI gates on."""

    def _run(self, tmp_path, old, new, *flags, old_name="CHAOS_r01.json",
             new_name="CHAOS_r02.json"):
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        a, b = tmp_path / old_name, tmp_path / new_name
        a.write_text(json.dumps(old))
        b.write_text(json.dumps(new))
        return subprocess.run(
            [sys.executable, os.path.join(repo, "scripts", "benchdiff.py"),
             str(a), str(b), *flags],
            capture_output=True, text=True, timeout=60,
        )

    def _chaos(self, **over) -> dict:
        base = {
            "metric": "chaos_heal_converge_s",
            "schema_version": bench.CHAOS_SCHEMA_VERSION,
            "value": 0.4,
            "crash": {"resurrection_hit_ratio": 0.95},
            "repair": {"converge_s": 0.4},
        }
        base.update(over)
        return base

    def test_identical_pair_exits_0(self, tmp_path):
        p = self._run(tmp_path, self._chaos(), self._chaos())
        assert p.returncode == bench.BENCHDIFF_EXIT_CLEAN, p.stdout + p.stderr
        assert "CLEAN" in p.stdout

    def test_regression_exits_1_and_names_the_metric(self, tmp_path):
        worse = self._chaos(value=2.0, repair={"converge_s": 2.0})
        p = self._run(tmp_path, self._chaos(), worse)
        assert p.returncode == bench.BENCHDIFF_EXIT_REGRESSION
        assert "repair.converge_s" in p.stdout

    def test_cross_kind_exits_2(self, tmp_path):
        obs = {"metric": "obs_stitched_node_tracks", "schema_version": 1,
               "value": 6}
        p = self._run(tmp_path, self._chaos(), obs,
                      new_name="OBS_r02.json")
        assert p.returncode == bench.BENCHDIFF_EXIT_MISMATCH

    def test_unreadable_input_exits_2(self, tmp_path):
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        a = tmp_path / "CHAOS_r01.json"
        a.write_text("{not json")
        p = subprocess.run(
            [sys.executable, os.path.join(repo, "scripts", "benchdiff.py"),
             str(a), str(a)],
            capture_output=True, text=True, timeout=60,
        )
        assert p.returncode == bench.BENCHDIFF_EXIT_MISMATCH

    def test_strict_flag_zeroes_thresholds(self, tmp_path):
        slightly = self._chaos(value=0.41, repair={"converge_s": 0.41})
        p0 = self._run(tmp_path, self._chaos(), slightly)
        assert p0.returncode == bench.BENCHDIFF_EXIT_CLEAN
        p1 = self._run(tmp_path, self._chaos(), slightly, "--strict")
        assert p1.returncode == bench.BENCHDIFF_EXIT_REGRESSION

    def test_json_output_carries_the_full_diff(self, tmp_path):
        worse = self._chaos(value=2.0, repair={"converge_s": 2.0})
        p = self._run(tmp_path, self._chaos(), worse, "--json")
        out = json.loads(p.stdout)
        assert out["status"] == "regression"
        assert any(r["verdict"] == "regression" for r in out["rows"])

    def test_real_checked_in_pair_diffs(self, tmp_path):
        """The sentinel runs on the actual bench trajectory: the two
        checked-in BENCH_FULL rounds compare without a schema refusal
        (clean or regression both prove the machinery; mismatch would
        mean the trajectory is not machine-comparable)."""
        import os
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        old = os.path.join(repo, "BENCH_FULL_r04.json")
        new = os.path.join(repo, "BENCH_FULL_r05.json")
        if not (os.path.exists(old) and os.path.exists(new)):
            pytest.skip("BENCH_FULL pair not checked in")
        p = subprocess.run(
            [sys.executable, os.path.join(repo, "scripts", "benchdiff.py"),
             old, new],
            capture_output=True, text=True, timeout=60,
        )
        assert p.returncode in (
            bench.BENCHDIFF_EXIT_CLEAN, bench.BENCHDIFF_EXIT_REGRESSION,
        ), p.stdout + p.stderr


class TestBlackboxArtifactSchema:
    """BLACKBOX v1 (PR 13, the flight-recorder plane): zero live
    findings on the healthy phase with every rule running, the
    post-mortem naming the hot shard + a crash window containing the
    kill from the observer dump and the unclean-death truncation from
    the victim's segment-only dump, and the sampler's self-accounted
    overhead under budget."""

    def _report(self) -> dict:
        from radixmesh_tpu.obs.doctor import RULES

        return {
            "schema_version": bench.BLACKBOX_SCHEMA_VERSION,
            "metric": "blackbox_postmortem_named",
            "value": bench.BLACKBOX_NAMED_TOTAL,
            "unit": "of 3 post-mortem verdicts named from dumps alone",
            "workload": "healthy + zipf storm + hot-owner hard kill",
            "nodes": 7,
            "topology": "4 prefill + 2 decode + 1 router + engine",
            "replication_factor": 3,
            "healthy": {
                "performed": True,
                "findings": [],
                "rules_checked": list(RULES),
                "inputs": {"mesh": True, "engine": True, "slo": True,
                           "attribution": True, "history": True},
                "history_samples": 12,
            },
            "storm": {"performed": True, "expected_hot_shard": 7},
            "crash": {
                "performed": True,
                "victim_rank": 2,
                "victim_is_hot_owner": True,
                "t_kill": 1000.0,
                "observer_detected_live": True,
            },
            "postmortem": {
                "observer": {
                    "hot_shard_named": True,
                    "hot_shard_evidence": {"shard": 7, "skew_peak": 18.0},
                    "crash_window_named": True,
                    "crash_evidence": {"window": [999.4, 1000.6]},
                },
                "victim": {
                    "truncation_named": True,
                    "unclean": True,
                    "segments": 2,
                },
                "expected": {"hot_shard": 7, "t_kill": 1000.0},
            },
            "history": {
                "interval_s": 0.25,
                "capacity": 900,
                "points": 4000,
                "self_overhead": {
                    "sample_seconds_total": 0.02,
                    "wall_s": 10.0,
                    "fraction": 0.002,
                    "budget_fraction": 0.01,
                    "under_budget": True,
                },
            },
            "blackbox": {"schema_version": 1},
            "wall_s": 10.0,
        }

    def test_complete_report_validates(self):
        assert bench.validate_blackbox(self._report()) == []

    def test_missing_top_fields_named(self):
        report = self._report()
        del report["postmortem"]
        del report["history"]
        problems = bench.validate_blackbox(report)
        assert "postmortem" in problems
        assert "history" in problems

    def test_healthy_findings_fail_the_gate(self):
        report = self._report()
        report["healthy"]["findings"] = [{"rule": "hot_shard"}]
        assert any(
            "healthy" in p for p in bench.validate_blackbox(report)
        )

    def test_all_rules_must_have_run_on_healthy(self):
        report = self._report()
        report["healthy"]["rules_checked"] = ["hot_shard"]
        problems = "\n".join(bench.validate_blackbox(report))
        assert "never ran" in problems

    def test_postmortem_misses_fail(self):
        for path, key in (
            (("postmortem", "observer"), "hot_shard_named"),
            (("postmortem", "observer"), "crash_window_named"),
            (("postmortem", "victim"), "truncation_named"),
            (("postmortem", "victim"), "unclean"),
        ):
            report = self._report()
            sec = report
            for p in path:
                sec = sec[p]
            sec[key] = False
            assert bench.validate_blackbox(report), (path, key)

    def test_kill_must_land_on_a_hot_owner(self):
        report = self._report()
        report["crash"]["victim_is_hot_owner"] = False
        problems = "\n".join(bench.validate_blackbox(report))
        assert "hot" in problems

    def test_overhead_budget_gate(self):
        report = self._report()
        report["history"]["self_overhead"]["fraction"] = 0.05
        report["history"]["self_overhead"]["under_budget"] = False
        problems = "\n".join(bench.validate_blackbox(report))
        assert "overhead" in problems

    def test_value_must_count_every_verdict(self):
        report = self._report()
        report["value"] = 2
        problems = "\n".join(bench.validate_blackbox(report))
        assert "verdicts" in problems

    def test_skipped_sections_are_schema_valid_but_gate_exempt(self):
        report = self._report()
        report["healthy"] = {"performed": False}
        report["crash"] = {"performed": False}
        assert bench.validate_blackbox(report) == []

    def test_build_report_matches_schema(self):
        core = {k: v for k, v in self._report().items()
                if k not in ("schema_version", "metric", "value", "unit",
                             "workload")}
        core["named"] = 3
        report = bench.build_blackbox_report(core)
        assert bench.validate_blackbox(report) == []
        assert report["value"] == 3
        assert report["metric"] == "blackbox_postmortem_named"

    def test_checked_in_artifact_validates_and_gates_green(self):
        import glob
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(repo, "BLACKBOX_r*.json")))
        assert paths, "no BLACKBOX artifact checked in"
        with open(paths[-1]) as fh:
            report = json.load(fh)
        assert bench.validate_blackbox(report) == [], paths[-1]
        assert "schema_violation" not in report
        assert report["value"] == bench.BLACKBOX_NAMED_TOTAL
        assert report["healthy"]["findings"] == []
        pm = report["postmortem"]
        # The post-mortem named the SEEDED shard, and the crash window
        # brackets the recorded kill instant.
        assert (
            pm["observer"]["hot_shard_evidence"]["shard"]
            == pm["expected"]["hot_shard"]
        )
        lo, hi = pm["observer"]["crash_evidence"]["window"]
        assert lo - 0.05 <= pm["expected"]["t_kill"] <= hi
        assert pm["victim"]["unclean"] is True
        assert report["history"]["self_overhead"]["fraction"] < 0.01

    def test_blackbox_kind_registered_in_sentinel(self):
        # COMPARE_RULES + metric-kind detection (the satellite-5 wiring).
        assert "BLACKBOX" in bench.COMPARE_RULES
        report = self._report()
        assert bench.artifact_kind(report) == "BLACKBOX"
        assert bench.artifact_kind({}, "BLACKBOX_r13.json") == "BLACKBOX"

    def test_compare_rounds_flags_lost_verdict(self):
        old = self._report()
        new = self._report()
        new["value"] = 2
        res = bench.compare_rounds(old, new, kind="BLACKBOX")
        assert res["status"] == "regression"
        assert "value" in res["regressions"]

    def test_compare_rounds_tolerates_overhead_jitter(self):
        old = self._report()
        new = self._report()
        new["history"]["self_overhead"]["fraction"] = 0.004  # 2x, in budget
        res = bench.compare_rounds(old, new, kind="BLACKBOX")
        assert res["status"] == "clean"

    def test_selfcheck_covers_the_blackbox_schema(self):
        res = bench.benchdiff_selfcheck()
        assert res["identical_clean"] is True
        assert res["regression_flagged"] is True
        assert res["mismatch_detected"] is True
        assert "BLACKBOX" in res["kinds_covered"]


class TestRebalanceArtifactSchema:
    """The REBALANCE artifact (PR 14, the closed robustness loop):
    zipf-storm skew strictly drops under rebalancing with zero failed
    requests mid-move, a mid-traffic router kill at N >= 2 routers
    loses nothing, and meshcheck reports the new plane clean."""

    def _report(self) -> dict:
        return {
            "schema_version": bench.REBALANCE_SCHEMA_VERSION,
            "metric": "rebalance_skew_drop_ratio",
            "value": 1.39,
            "unit": "zipf-storm skew before / after heat-driven rebalancing",
            "workload": "zipf storm + router kill (run_chaos_workload)",
            "nodes": 8,
            "topology": "4 prefill + 2 decode + 2 routers (inproc)",
            "replication_factor": 2,
            "rebalance": {
                "performed": True, "skew_before": 20.3, "skew_after": 14.6,
                "skew_dropped": True, "moves": 4,
                "max_moves_per_round": 4, "moves_bounded": True,
                "boosted_shards": [19, 42], "hot_shard": 19,
                "attempted_mid_move": 175, "ok_mid_move": 175,
                "failed_mid_move": 0, "overrides_version": 1,
                "overrides_converged": True, "handoff_entries": 8,
                "requests_wave1": 155, "rebalance_s": 6.0,
            },
            "router_kill": {
                "performed": True, "routers": 2, "killed": "cr0",
                "survivor": "cr1", "streams": 10, "inflight_at_kill": 10,
                "completed": 10, "failed": 0, "failovers": 1, "hedges": 1,
                "survivor_served": True, "router_kill_s": 0.4,
            },
            "meshcheck": {
                "files": ["cache/rebalance.py", "router/front_door.py"],
                "findings": 0, "clean": True, "detail": [],
            },
            "wall_s": 11.6,
        }

    def test_complete_report_validates(self):
        assert bench.validate_rebalance(self._report()) == []
        assert bench.validate_rebalance(7) == ["artifact is not a JSON object"]

    def test_missing_fields_are_named(self):
        report = self._report()
        del report["replication_factor"]
        del report["rebalance"]["overrides_converged"]
        del report["router_kill"]["failovers"]
        del report["meshcheck"]["clean"]
        missing = bench.validate_rebalance(report)
        assert "replication_factor" in missing
        assert "rebalance.overrides_converged" in missing
        assert "router_kill.failovers" in missing
        assert "meshcheck.clean" in missing

    def test_gates_enforced(self):
        report = self._report()
        report["rebalance"]["skew_after"] = 25.0
        report["router_kill"]["failed"] = 2
        report["meshcheck"]["clean"] = False
        report["meshcheck"]["findings"] = 3
        problems = "\n".join(bench.validate_rebalance(report))
        assert "did not strictly drop" in problems
        assert "LOST to the router kill" in problems
        assert "statically clean" in problems

    def test_value_gate(self):
        report = self._report()
        report["value"] = 0.9
        problems = "\n".join(bench.validate_rebalance(report))
        assert "not > 1" in problems

    def test_skipped_sections_gate_exempt(self):
        report = self._report()
        report["rebalance"] = {"performed": False}
        report["router_kill"] = {"performed": False}
        report["value"] = 0.0
        assert bench.validate_rebalance(report) == []

    def test_non_dict_sections_are_violations(self):
        """A present-but-garbage section must not silently skip every
        gate and validate clean."""
        report = self._report()
        report["rebalance"] = True
        report["router_kill"] = "done"
        report["meshcheck"] = None
        problems = "\n".join(bench.validate_rebalance(report))
        assert "rebalance section is not an object" in problems
        assert "router_kill section is not an object" in problems
        assert "meshcheck section is not an object" in problems

    def test_build_report_matches_schema(self):
        res = {
            "nodes": 8,
            "topology": "4 prefill + 2 decode + 2 routers (inproc)",
            "replication_factor": 2,
            "rebalance": self._report()["rebalance"],
            "router_kill": self._report()["router_kill"],
            "wall_s": 11.6,
        }
        report = bench.build_rebalance_report(
            res, meshcheck=self._report()["meshcheck"]
        )
        assert bench.validate_rebalance(report) == []
        assert report["value"] == round(20.3 / 14.6, 4)

    def test_build_report_without_meshcheck_fails_the_gate(self):
        # A missing verdict must read as NOT clean, never as vacuously
        # green.
        res = {
            "nodes": 8, "topology": "t", "replication_factor": 2,
            "rebalance": self._report()["rebalance"],
            "router_kill": self._report()["router_kill"], "wall_s": 1.0,
        }
        report = bench.build_rebalance_report(res)
        problems = "\n".join(bench.validate_rebalance(report))
        assert "statically clean" in problems

    def test_rebalance_kind_registered_in_sentinel(self):
        assert "REBALANCE" in bench.COMPARE_RULES
        assert bench.artifact_kind(self._report()) == "REBALANCE"
        assert (
            bench.artifact_kind({}, "REBALANCE_r14.json") == "REBALANCE"
        )

    def test_compare_rounds_flags_regressions(self):
        old = self._report()
        new = self._report()
        new["rebalance"]["failed_mid_move"] = 2
        res = bench.compare_rounds(old, new, kind="REBALANCE")
        assert res["status"] == "regression"
        assert "rebalance.failed_mid_move" in res["regressions"]

    def test_checked_in_artifact_validates(self):
        import glob
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(repo, "REBALANCE_r*.json")))
        assert paths, "no REBALANCE artifact checked in"
        with open(paths[-1]) as fh:
            report = json.load(fh)
        assert bench.validate_rebalance(report) == []
        assert report["rebalance"]["performed"] is True
        assert report["router_kill"]["performed"] is True
        assert report["meshcheck"]["findings"] == 0


class TestTierArtifactSchema:
    """The TIER artifact (PR 15, the durable KV spill tier): hit-rate
    at a working set >= 10x host capacity beats the no-tier baseline,
    decode never blocks on disk restores, the whole-cell kill-and-
    restart drill resumes every stream byte-identical from disk alone
    with seeded corrupt/torn extents detected and never served, and
    meshcheck reports the tier plane clean."""

    def _report(self) -> dict:
        return {
            "schema_version": bench.TIER_SCHEMA_VERSION,
            "metric": "tier_hit_rate_gain",
            "value": 16.0,
            "unit": "tier hit-rate / no-tier baseline at 12x host capacity",
            "workload": "zipf re-visit + overlap + cold-cell drill",
            "capacity": {
                "working_set_tokens": 6144, "host_slots": 512,
                "working_set_ratio": 12.0, "tier_hit_rate": 0.99,
                "baseline_hit_rate": 0.06, "hit_rate_gain": 16.0,
                "requests": 32, "distinct_prefixes": 16,
            },
            "spill": {
                "spilled_tokens": 6144, "extents": 16, "demotes": 16,
                "promotes": 15, "drops": 0, "resident_bytes": 3_000_000,
            },
            "restore_overlap": {
                "parked_requests": 3, "disk_restored_tokens": 6912,
                "decode_steps_during_restore": 2,
                "max_decode_gap_s": 1.5, "overlap_ok": True,
            },
            "cold_start": {
                "performed": True, "interrupted": 5, "resumed": 5,
                "byte_identical": True, "failed": 0,
                "disk_hit_tokens": 2064, "grafted_nodes": 4,
                "orphaned": 0, "corrupt_detected": 2,
                "corrupt_served": 0, "restart_s": 0.005,
            },
            "corruption": {
                "extents_attacked": 2, "truncated": 1, "bitflipped": 1,
                "detected": 2, "served_corrupt": 0,
            },
            "meshcheck": {
                "files": ["cache/kv_tier.py"], "findings": 0,
                "clean": True,
            },
            "page_size": 4,
            "wall_s": 9.3,
        }

    def test_complete_report_validates(self):
        assert bench.validate_tier(self._report()) == []
        assert bench.validate_tier(7) == ["artifact is not a JSON object"]

    def test_missing_fields_are_named(self):
        report = self._report()
        del report["capacity"]["working_set_ratio"]
        del report["cold_start"]["byte_identical"]
        del report["corruption"]["served_corrupt"]
        missing = bench.validate_tier(report)
        assert "capacity.working_set_ratio" in missing
        assert "cold_start.byte_identical" in missing
        assert "corruption.served_corrupt" in missing

    def test_capacity_gates(self):
        report = self._report()
        report["capacity"]["working_set_ratio"] = 4.0
        report["capacity"]["tier_hit_rate"] = 0.05
        problems = "\n".join(bench.validate_tier(report))
        assert "10.0x" in problems
        assert "does not beat" in problems

    def test_cold_start_gates(self):
        report = self._report()
        report["cold_start"]["failed"] = 1
        report["cold_start"]["resumed"] = 4
        report["cold_start"]["byte_identical"] = False
        report["cold_start"]["corrupt_served"] = 1
        report["cold_start"]["corrupt_detected"] = 0
        report["cold_start"]["disk_hit_tokens"] = 0
        problems = "\n".join(bench.validate_tier(report))
        assert "must lose nothing" in problems
        assert "resumed 4 != interrupted 5" in problems
        assert "byte-identical" in problems
        assert "SERVED" in problems
        assert "was not detected" in problems
        assert "never actually read the durable tier" in problems

    def test_overlap_gates(self):
        report = self._report()
        report["restore_overlap"]["parked_requests"] = 0
        report["restore_overlap"]["decode_steps_during_restore"] = 0
        report["restore_overlap"]["overlap_ok"] = False
        problems = "\n".join(bench.validate_tier(report))
        assert "zero parked disk restores" in problems
        assert "decode made zero progress" in problems

    def test_corruption_gates(self):
        report = self._report()
        report["corruption"]["detected"] = 1
        problems = "\n".join(bench.validate_tier(report))
        assert "1 of 2 attacked" in problems

    def test_meshcheck_and_value_gates(self):
        report = self._report()
        report["meshcheck"]["clean"] = False
        report["meshcheck"]["findings"] = 2
        report["value"] = 0.8
        problems = "\n".join(bench.validate_tier(report))
        assert "statically clean" in problems
        assert "not > 1" in problems

    def test_skipped_cold_start_gate_exempt(self):
        report = self._report()
        report["cold_start"] = {"performed": False}
        report["corruption"]["extents_attacked"] = 0
        assert bench.validate_tier(report) == []

    def test_non_dict_sections_are_violations(self):
        report = self._report()
        report["cold_start"] = "done"
        problems = "\n".join(bench.validate_tier(report))
        assert "cold_start section is not an object" in problems

    def test_build_report_matches_schema(self):
        base = self._report()
        res = {
            k: base[k]
            for k in (
                "capacity", "spill", "restore_overlap", "cold_start",
                "corruption", "page_size", "wall_s",
            )
        }
        report = bench.build_tier_report(res, meshcheck=base["meshcheck"])
        assert bench.validate_tier(report) == []
        assert report["value"] == base["capacity"]["hit_rate_gain"]

    def test_build_report_without_meshcheck_fails_the_gate(self):
        base = self._report()
        res = {
            k: base[k]
            for k in (
                "capacity", "spill", "restore_overlap", "cold_start",
                "corruption", "page_size", "wall_s",
            )
        }
        problems = "\n".join(bench.validate_tier(bench.build_tier_report(res)))
        assert "statically clean" in problems

    def test_tier_kind_registered_in_sentinel(self):
        assert "TIER" in bench.COMPARE_RULES
        assert bench.artifact_kind(self._report()) == "TIER"
        assert bench.artifact_kind({}, "TIER_r15.json") == "TIER"
        res = bench.benchdiff_selfcheck()
        assert "TIER" in res["kinds_covered"]

    def test_compare_rounds_flags_corrupt_served(self):
        old = self._report()
        new = self._report()
        new["cold_start"]["corrupt_served"] = 1
        res = bench.compare_rounds(old, new, kind="TIER")
        assert res["status"] == "regression"
        assert "cold_start.corrupt_served" in res["regressions"]

    def test_checked_in_artifact_validates(self):
        import glob
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(repo, "TIER_r*.json")))
        assert paths, "no TIER artifact checked in"
        with open(paths[-1]) as fh:
            report = json.load(fh)
        assert bench.validate_tier(report) == []
        assert report["cold_start"]["performed"] is True
        assert report["cold_start"]["byte_identical"] is True
        assert report["corruption"]["served_corrupt"] == 0
        assert report["meshcheck"]["findings"] == 0
        # The new lint invariant's positive control demonstrably trips
        # in the artifact's meshcheck verdict.
        assert report["meshcheck"]["file_io_controls_tripped"] >= 1


class TestDoctorRuleVersionGating:
    """DOCTOR/BLACKBOX v3 (PR 15): artifacts validate against the rule
    set pinned for THEIR schema version — a checked-in v1/v2 artifact
    can never retroactively have run tier_thrash."""

    def test_v1_requires_the_pinned_six(self):
        from radixmesh_tpu.obs.doctor import RULES

        req = bench._required_doctor_rules({"schema_version": 1}, RULES)
        assert tuple(req) == bench.DOCTOR_RULES_V1

    def test_v2_requires_the_pinned_seven(self):
        from radixmesh_tpu.obs.doctor import RULES

        req = bench._required_doctor_rules({"schema_version": 2}, RULES)
        assert tuple(req) == bench.DOCTOR_RULES_V2
        assert "tier_thrash" not in req

    def test_v3_requires_every_live_rule(self):
        from radixmesh_tpu.obs.doctor import RULES

        req = bench._required_doctor_rules(
            {"schema_version": bench.DOCTOR_SCHEMA_VERSION}, RULES
        )
        assert "tier_thrash" in req
        assert tuple(req) == RULES

    def test_v4_pins_pr17_rules_without_token_plane(self):
        from radixmesh_tpu.obs.doctor import RULES

        req = bench._required_doctor_rules({"schema_version": 4}, RULES)
        assert tuple(req) == bench.DOCTOR_RULES_V4
        assert "straggler_node" in req
        assert "decode_stall" not in req


class TestSpecArtifactSchema:
    """The SPEC artifact (PR 18, the speedometer): draft-token
    conservation on every verify path with per-shape and per-draft-
    source breakdowns, seeded-stall ITL attribution, the adaptive-γ
    goodput A-B, and the token-timeline overhead bound — the artifact
    ROADMAP item 1's gate names."""

    def _report(self) -> dict:
        return {
            "schema_version": bench.SPEC_SCHEMA_VERSION,
            "metric": "spec_accepted_tokens_per_step",
            "value": 1.6,
            "unit": "draft tokens accepted per verify wave",
            "workload": "repetitive + replayed prompts, tiny CPU model",
            "acceptance": {
                "performed": True, "proposed": 120, "accepted": 72,
                "rejected": 48, "conserved": True,
                "accepted_per_step": 1.6, "waves": 45,
                "by_shape": {
                    "p32": {"proposed": 60, "accepted": 30, "rejected": 30,
                            "acceptance": 0.5},
                    "p64": {"proposed": 60, "accepted": 42, "rejected": 18,
                            "acceptance": 0.7},
                },
                "by_source": {
                    "tree": {"proposed": 54, "accepted": 54, "rejected": 0,
                             "acceptance": 1.0},
                    "ngram": {"proposed": 66, "accepted": 18, "rejected": 48,
                              "acceptance": 0.2727},
                },
            },
            "itl": {
                "performed": True, "count": 196, "p50_s": 0.004,
                "p99_s": 1.9, "stalls": {"scheduler_wait": 9},
                "stall_seconds": {"scheduler_wait": 11.2},
                "seeded_cause": "scheduler_wait", "seeded_detected": True,
            },
            "adaptive": {
                "performed": True, "gamma_base": 4,
                "fixed_goodput_tps": 1900.0,
                "adaptive_goodput_tps": 2050.0, "goodput_ratio": 1.0789,
                "no_worse": True, "fixed_acceptance": 0.87,
                "adaptive_acceptance": 0.94,
            },
            "overhead": {
                "tokens": 1000, "timeline_on_s": 0.0019,
                "timeline_off_s": 0.0001, "fraction": 0.0018,
                "budget_fraction": 0.01, "under_budget": True,
            },
            "wall_s": 12.8,
        }

    def test_complete_report_validates(self):
        assert bench.validate_spec(self._report()) == []
        assert bench.validate_spec(7) == ["artifact is not a JSON object"]

    def test_missing_fields_are_named(self):
        report = self._report()
        del report["wall_s"]
        del report["acceptance"]["conserved"]
        del report["itl"]["seeded_detected"]
        del report["adaptive"]["goodput_ratio"]
        del report["overhead"]["fraction"]
        missing = bench.validate_spec(report)
        assert "wall_s" in missing
        assert "acceptance.conserved" in missing
        assert "itl.seeded_detected" in missing
        assert "adaptive.goodput_ratio" in missing
        assert "overhead.fraction" in missing

    def test_conservation_gates(self):
        report = self._report()
        report["acceptance"]["conserved"] = False
        report["acceptance"]["accepted"] = 70
        problems = "\n".join(bench.validate_spec(report))
        assert "conservation broke" in problems
        report = self._report()
        report["acceptance"]["proposed"] = 0
        problems = "\n".join(bench.validate_spec(report))
        assert "zero proposed draft tokens" in problems
        report = self._report()
        report["acceptance"]["accepted_per_step"] = 0.0
        report["value"] = 0.0
        problems = "\n".join(bench.validate_spec(report))
        assert "every draft missed" in problems
        assert "not > 0" in problems

    def test_empty_breakdowns_are_violations(self):
        report = self._report()
        report["acceptance"]["by_shape"] = {}
        report["acceptance"]["by_source"] = {}
        problems = "\n".join(bench.validate_spec(report))
        assert "by_shape is empty" in problems
        assert "by_source is empty" in problems

    def test_itl_gates(self):
        report = self._report()
        report["itl"]["count"] = 0
        report["itl"]["seeded_detected"] = False
        report["itl"]["p99_s"] = 0.001
        problems = "\n".join(bench.validate_spec(report))
        assert "zero timed inter-token gaps" in problems
        assert "'scheduler_wait' stall was not attributed" in problems
        assert "p99 0.001 < p50 0.004" in problems

    def test_adaptive_and_overhead_gates(self):
        report = self._report()
        report["adaptive"]["no_worse"] = False
        report["adaptive"]["goodput_ratio"] = 0.7
        report["overhead"]["under_budget"] = False
        report["overhead"]["fraction"] = 0.04
        problems = "\n".join(bench.validate_spec(report))
        assert "the controller costs more than it saves" in problems
        assert "may not slow the car" in problems

    def test_skipped_sections_gate_exempt(self):
        # performed=False sections are schema-valid but gate-exempt
        # (the CHAOS convention) — a partial run still emits a valid,
        # honestly-labelled artifact. Overhead has no performed flag:
        # the bound is cheap enough to always measure.
        report = self._report()
        report["acceptance"] = {"performed": False}
        report["itl"] = {"performed": False}
        report["adaptive"] = {"performed": False}
        report["value"] = None
        assert bench.validate_spec(report) == []

    def test_non_dict_sections_are_violations(self):
        report = self._report()
        report["acceptance"] = "done"
        report["overhead"] = 3
        problems = "\n".join(bench.validate_spec(report))
        assert "acceptance section is not an object" in problems
        assert "overhead section is not an object" in problems

    def test_build_report_matches_schema(self):
        base = self._report()
        res = {
            k: base[k]
            for k in ("acceptance", "itl", "adaptive", "overhead", "wall_s")
        }
        report = bench.build_spec_report(res)
        assert bench.validate_spec(report) == []
        assert report["value"] == base["acceptance"]["accepted_per_step"]
        assert report["metric"] == "spec_accepted_tokens_per_step"

    def test_spec_kind_registered_in_sentinel(self):
        assert "SPEC" in bench.COMPARE_RULES
        assert bench.artifact_kind(self._report()) == "SPEC"
        assert bench.artifact_kind({}, "SPEC_r18.json") == "SPEC"
        res = bench.benchdiff_selfcheck()
        assert "SPEC" in res["kinds_covered"]

    def test_compare_rounds_flags_acceptance_drop(self):
        old = self._report()
        new = self._report()
        new["value"] = 0.9
        new["acceptance"]["accepted_per_step"] = 0.9
        res = bench.compare_rounds(old, new, kind="SPEC")
        assert res["status"] == "regression"
        assert "acceptance.accepted_per_step" in res["regressions"]

    def test_checked_in_artifact_validates(self):
        import glob
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(repo, "SPEC_r*.json")))
        assert paths, "no SPEC artifact checked in"
        with open(paths[-1]) as fh:
            report = json.load(fh)
        assert bench.validate_spec(report) == []
        assert report["acceptance"]["conserved"] is True
        assert report["acceptance"]["by_shape"]
        assert report["acceptance"]["by_source"]
        assert report["itl"]["seeded_detected"] is True
        assert report["adaptive"]["no_worse"] is True
        assert report["overhead"]["under_budget"] is True


class TestConvoyArtifactSchema:
    """The CONVOY artifact (PR 19, killing the prefill convoy):
    decode-interleaved chunked prefill's TTFT win with bit-identical
    outputs, the prefill_convoy stall drop, the wave-counted starvation
    bound, and the paged/dense crossover — the ISSUE's gate names."""

    def _report(self) -> dict:
        return {
            "schema_version": bench.CONVOY_SCHEMA_VERSION,
            "metric": "convoy_ttft_speedup",
            "value": 4.2,
            "unit": "late-arrival p50 TTFT ratio (legacy / mixed waves)",
            "workload": "carrier + 960-token convoy + late 16-token "
            "arrival, A-B across prefill_inline_budget",
            "interleave": {
                "performed": True, "reps": 5, "inline_budget": 32,
                "base_ttft_p50_s": 0.18, "mixed_ttft_p50_s": 0.043,
                "ttft_ratio": 4.2, "base_itl_p99_s": 0.09,
                "mixed_itl_p99_s": 0.05, "outputs_match": True,
                "base_accepted_per_wave": 0.36,
                "mixed_accepted_per_wave": 0.35,
                "waves": {"counts": {"mixed": 120, "boost": 0},
                          "inline_tokens": 3904},
            },
            "stalls": {
                "performed": True, "stall_threshold_s": 0.02,
                "base_convoy_s_per_req": 0.058,
                "mixed_convoy_s_per_req": 0.002,
                "convoy_drop_ratio": 29.0,
                "base_causes": {"prefill_convoy": 0.52},
                "mixed_causes": {"prefill_inline": 0.4},
                "inline_attributed_s": 0.4,
            },
            "starvation": {
                "performed": True, "skew": "320:16",
                "max_defer_bound": 2, "max_step_gap": 1,
                "max_defer_observed": 2, "boost_waves": 2,
                "bounded": True, "carrier_tokens": 48,
            },
            "crossover": {
                "performed": True, "paged_min_batch": 16,
                "sweep": [
                    {"batch": 2, "bucket": 2, "paged_selected": False,
                     "effective_over_dense": 1.0,
                     "bucketed_over_direct": 1.01},
                    {"batch": 32, "bucket": 32, "paged_selected": False,
                     "effective_over_dense": 1.0,
                     "bucketed_over_direct": 0.99},
                ],
                "small_batch_ok": True,
                "large_batch_ok": True,
            },
            "wall_s": 40.0,
        }

    def test_complete_report_validates(self):
        assert bench.validate_convoy(self._report()) == []
        assert bench.validate_convoy(7) == ["artifact is not a JSON object"]

    def test_missing_fields_are_named(self):
        report = self._report()
        del report["wall_s"]
        del report["interleave"]["ttft_ratio"]
        del report["stalls"]["convoy_drop_ratio"]
        del report["starvation"]["bounded"]
        del report["crossover"]["small_batch_ok"]
        missing = bench.validate_convoy(report)
        assert "wall_s" in missing
        assert "interleave.ttft_ratio" in missing
        assert "stalls.convoy_drop_ratio" in missing
        assert "starvation.bounded" in missing
        assert "crossover.small_batch_ok" in missing

    def test_interleave_gates(self):
        report = self._report()
        report["interleave"]["ttft_ratio"] = 1.1
        report["interleave"]["outputs_match"] = False
        problems = "\n".join(bench.validate_convoy(report))
        assert "did not beat the convoy" in problems
        assert "outputs diverged" in problems
        report = self._report()
        report["interleave"]["mixed_itl_p99_s"] = 0.5
        report["interleave"]["mixed_accepted_per_wave"] = 0.1
        problems = "\n".join(bench.validate_convoy(report))
        assert "bought by starving decode" in problems
        assert "breaking speculation" in problems

    def test_stall_gates(self):
        report = self._report()
        report["stalls"]["convoy_drop_ratio"] = 1.2
        report["stalls"]["base_causes"] = {}
        problems = "\n".join(bench.validate_convoy(report))
        assert "the convoy survived" in problems
        assert "base_causes decomposition is empty" in problems

    def test_starvation_gates(self):
        report = self._report()
        report["starvation"]["bounded"] = False
        report["starvation"]["max_step_gap"] = 7
        problems = "\n".join(bench.validate_convoy(report))
        assert "starvation bound broke" in problems
        report = self._report()
        report["starvation"]["boost_waves"] = 0
        problems = "\n".join(bench.validate_convoy(report))
        assert "proven vacuously" in problems

    def test_crossover_gates(self):
        report = self._report()
        report["crossover"]["small_batch_ok"] = False
        report["crossover"]["large_batch_ok"] = False
        report["crossover"]["sweep"] = []
        problems = "\n".join(bench.validate_convoy(report))
        assert "picking the slow path" in problems
        assert "padding is costing" in problems
        assert "empty sweep" in problems

    def test_skipped_sections_gate_exempt(self):
        report = self._report()
        for section in ("interleave", "stalls", "starvation", "crossover"):
            report[section] = {"performed": False}
        report["value"] = None
        assert bench.validate_convoy(report) == []

    def test_non_dict_sections_are_violations(self):
        report = self._report()
        report["interleave"] = "done"
        report["crossover"] = 3
        problems = "\n".join(bench.validate_convoy(report))
        assert "interleave section is not an object" in problems
        assert "crossover section is not an object" in problems

    def test_build_report_matches_schema(self):
        base = self._report()
        res = {
            k: base[k]
            for k in ("interleave", "stalls", "starvation", "crossover",
                      "wall_s")
        }
        report = bench.build_convoy_report(res)
        assert bench.validate_convoy(report) == []
        assert report["value"] == base["interleave"]["ttft_ratio"]
        assert report["metric"] == "convoy_ttft_speedup"

    def test_convoy_kind_registered_in_sentinel(self):
        assert "CONVOY" in bench.COMPARE_RULES
        assert bench.artifact_kind(self._report()) == "CONVOY"
        assert bench.artifact_kind({}, "CONVOY_r19.json") == "CONVOY"
        res = bench.benchdiff_selfcheck()
        assert "CONVOY" in res["kinds_covered"]
        assert res["identical_clean"] and res["regression_flagged"]
        assert res["mismatch_detected"]

    def test_compare_rounds_flags_ttft_collapse(self):
        old = self._report()
        new = self._report()
        new["value"] = 1.6
        new["interleave"]["ttft_ratio"] = 1.6
        res = bench.compare_rounds(old, new, kind="CONVOY")
        assert res["status"] == "regression"
        assert "interleave.ttft_ratio" in res["regressions"]

    def test_checked_in_artifact_validates(self):
        import glob
        import os

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(repo, "CONVOY_r*.json")))
        assert paths, "no CONVOY artifact checked in"
        with open(paths[-1]) as fh:
            report = json.load(fh)
        assert bench.validate_convoy(report) == []
        assert report["interleave"]["outputs_match"] is True
        assert report["starvation"]["bounded"] is True
        assert report["crossover"]["small_batch_ok"] is True
