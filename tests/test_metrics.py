"""Observability subsystem: metric semantics, exposition format, and the
hooks wired into the engine/mesh/router (SURVEY §5 — the reference ships no
metrics; ``TreeNode.hit_count`` is never incremented, ``radix_cache.py:47``)."""

import threading

import numpy as np
import pytest

from radixmesh_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
    set_registry,
)
from radixmesh_tpu.obs.tracing import annotate, timed


@pytest.fixture(autouse=True)
def fresh_registry():
    """Isolate the process-wide registry per test."""
    old = get_registry()
    reg = set_registry(Registry())
    yield reg
    set_registry(old)


class TestCounter:
    def test_inc(self):
        c = Counter("c")
        c.inc()
        c.inc(4)
        assert c.value == 5

    def test_negative_rejected(self):
        with pytest.raises(ValueError):
            Counter("c").inc(-1)

    def test_labels(self):
        c = Counter("c", label_names=("op",))
        c.labels(op="a").inc()
        c.labels(op="a").inc()
        c.labels(op="b").inc(7)
        assert c.labels(op="a").value == 2
        assert c.labels(op="b").value == 7

    def test_wrong_labels_rejected(self):
        c = Counter("c", label_names=("op",))
        with pytest.raises(ValueError):
            c.labels(other="x")


class TestGauge:
    def test_set_inc_dec(self):
        g = Gauge("g")
        g.set(10)
        g.inc(2)
        g.dec(5)
        assert g.value == 7


class TestHistogram:
    def test_cumulative_buckets(self):
        h = Histogram("h", buckets=(1.0, 10.0))
        for v in (0.5, 0.7, 5.0, 100.0):
            h.observe(v)
        assert h.count == 4
        assert h.sum == pytest.approx(106.2)
        text = Registry().render()  # empty registry renders fine
        assert text == "\n"

    def test_quantile(self):
        h = Histogram("h", buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 1.7, 3.0):
            h.observe(v)
        assert h.quantile(0.5) == 2.0
        assert h.quantile(1.0) == 4.0

    def test_timer(self):
        h = Histogram("h")
        with h.time():
            pass
        assert h.count == 1


class TestRegistry:
    def test_idempotent_registration(self, fresh_registry):
        reg = fresh_registry
        a = reg.counter("x_total", "help")
        b = reg.counter("x_total")
        assert a is b

    def test_type_clash_rejected(self, fresh_registry):
        reg = fresh_registry
        reg.counter("x")
        with pytest.raises(ValueError):
            reg.gauge("x")

    def test_render_exposition(self, fresh_registry):
        reg = fresh_registry
        reg.counter("req_total", "requests", ("code",)).labels(code="200").inc(3)
        reg.gauge("temp").set(1.5)
        h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        text = reg.render()
        assert "# TYPE req_total counter" in text
        assert 'req_total{code="200"} 3' in text
        assert "# TYPE temp gauge" in text
        assert "temp 1.5" in text
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="1"} 1' in text
        assert 'lat_seconds_bucket{le="+Inf"} 2' in text
        assert "lat_seconds_count 2" in text

    def test_snapshot(self, fresh_registry):
        reg = fresh_registry
        reg.counter("a").inc(2)
        reg.histogram("h").observe(1.0)
        snap = reg.snapshot()
        assert snap["a"] == 2
        assert snap["h_count"] == 1

    def test_thread_safety_smoke(self, fresh_registry):
        c = fresh_registry.counter("c")

        def worker():
            for _ in range(1000):
                c.inc()

        ts = [threading.Thread(target=worker) for _ in range(8)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert c.value == 8000


class TestTracing:
    def test_annotate_noop(self):
        with annotate("span"):
            pass

    def test_timed_observes(self):
        h = Histogram("h")
        with timed(h, "x"):
            pass
        assert h.count == 1


class TestOplogTimestamp:
    def test_ts_round_trips(self):
        from radixmesh_tpu.cache.oplog import Oplog, OplogType, deserialize, serialize

        op = Oplog(
            op_type=OplogType.INSERT,
            origin_rank=1,
            logic_id=7,
            ttl=3,
            key=np.arange(4, dtype=np.int32),
            value=np.arange(4, dtype=np.int32),
            value_rank=1,
            ts=1234.5,
        )
        assert deserialize(serialize(op)).ts == 1234.5


class TestEngineMetrics:
    def test_engine_populates_registry(self, fresh_registry):
        from radixmesh_tpu.engine.engine import Engine
        from radixmesh_tpu.models.llama import ModelConfig, init_params
        import jax

        cfg = ModelConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = Engine(cfg, params, num_slots=512, page_size=4, max_batch=2, name="e0")
        prompt = list(range(1, 20))
        eng.generate([prompt], max_steps=30)
        eng.generate([prompt], max_steps=30)  # second pass hits the cache
        snap = fresh_registry.snapshot()
        k = '{engine="e0"}'
        assert snap[f"engine_prompt_tokens_total{k}"] == 2 * len(prompt)
        assert snap[f"engine_cached_tokens_total{k}"] > 0
        assert snap[f"engine_generated_tokens_total{k}"] > 0
        assert snap[f"engine_ttft_seconds{k}_count"] == 2
        assert snap[f"engine_tpot_seconds{k}_count"] >= 1
        # counter == stats (the stop-token path must not diverge)
        assert snap[f"engine_generated_tokens_total{k}"] == eng.stats.generated_tokens


class TestMeshMetrics:
    def test_ring_populates_lag_and_counters(self, fresh_registry):
        from radixmesh_tpu.comm.inproc import InprocHub
        from tests.test_mesh_cache import Cluster, insert_with_pool, wait_for

        InprocHub.reset_default()
        c = Cluster()
        try:
            c.wait_ready()
            prefill = c.node(1)
            insert_with_pool(prefill, [1, 2, 3])
            assert wait_for(
                lambda: all(
                    n.match_prefix([1, 2, 3]).length == 3 for n in c.ring_nodes
                )
            )
            snap = fresh_registry.snapshot()
            lag = [
                v
                for k, v in snap.items()
                if k.startswith("mesh_oplog_lag_seconds") and k.endswith("_count")
            ]
            assert sum(lag) > 0
            sent = [v for k, v in snap.items() if k.startswith("mesh_oplogs_sent")]
            assert sum(sent) > 0
            assert prefill.metrics["oplogs_sent"] > 0
            received = [
                k
                for k in snap
                if k.startswith("mesh_oplogs_received_total") and "INSERT" in k
            ]
            assert received
        finally:
            c.close()
            InprocHub.reset_default()
