"""Observability subsystem: metric semantics, exposition format, and the
hooks wired into the engine/mesh/router (SURVEY §5 — the reference ships no
metrics; ``TreeNode.hit_count`` is never incremented, ``radix_cache.py:47``)."""

import threading

import numpy as np
import pytest

from radixmesh_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
    set_registry,
)
from radixmesh_tpu.obs.tracing import annotate, timed

pytestmark = pytest.mark.quick


@pytest.fixture(autouse=True)
def fresh_registry():
    """Isolate the process-wide registry per test."""
    old = get_registry()
    reg = set_registry(Registry())
    yield reg
    set_registry(old)


class TestCounter:
    def test_inc(self):
        c = Counter("c")
        c.inc()
        c.inc(4)
        assert c.value == 5

    def test_negative_rejected(self):
        with pytest.raises(ValueError):
            Counter("c").inc(-1)

    def test_labels(self):
        c = Counter("c", label_names=("op",))
        c.labels(op="a").inc()
        c.labels(op="a").inc()
        c.labels(op="b").inc(7)
        assert c.labels(op="a").value == 2
        assert c.labels(op="b").value == 7

    def test_wrong_labels_rejected(self):
        c = Counter("c", label_names=("op",))
        with pytest.raises(ValueError):
            c.labels(other="x")


class TestGauge:
    def test_set_inc_dec(self):
        g = Gauge("g")
        g.set(10)
        g.inc(2)
        g.dec(5)
        assert g.value == 7


class TestHistogram:
    def test_cumulative_buckets(self):
        h = Histogram("h", buckets=(1.0, 10.0))
        for v in (0.5, 0.7, 5.0, 100.0):
            h.observe(v)
        assert h.count == 4
        assert h.sum == pytest.approx(106.2)
        text = Registry().render()  # empty registry renders fine
        assert text == "\n"

    def test_quantile_interpolates_within_bucket(self):
        # p50 used to snap to the bucket's upper bound (2.0 here); the
        # interpolated estimate assumes uniform mass within the bucket.
        h = Histogram("h", buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 1.7, 3.0):
            h.observe(v)
        # target = 2 of 4 samples; bucket (1, 2] holds samples #2-3, so
        # the estimate is 1 + (2-1) * (2-1)/2.
        assert h.quantile(0.5) == pytest.approx(1.5)
        assert h.quantile(1.0) == pytest.approx(4.0)

    def test_quantile_first_bucket_interpolates_from_zero(self):
        h = Histogram("h", buckets=(10.0, 20.0))
        for _ in range(4):
            h.observe(5.0)
        # All mass in (0, 10]: the p50 estimate is 10 * 0.5, not the
        # bucket edge.
        assert h.quantile(0.5) == pytest.approx(5.0)

    def test_quantile_overflow_returns_largest_finite_bound(self):
        h = Histogram("h", buckets=(1.0, 2.0))
        h.observe(100.0)  # lands in +Inf
        assert h.quantile(0.5) == 2.0

    def test_observe_bucket_edges_match_cumulative_semantics(self):
        # value == upper bound must land IN that bucket (<= semantics);
        # the bisect rewrite must not flip edges to the next bucket.
        h = Histogram("h", buckets=(1.0, 2.0, 4.0))
        for v in (1.0, 2.0, 4.0, 4.1):
            h.observe(v)
        assert h._counts == [1, 1, 1, 1]

    def test_timer(self):
        h = Histogram("h")
        with h.time():
            pass
        assert h.count == 1


class TestRegistry:
    def test_idempotent_registration(self, fresh_registry):
        reg = fresh_registry
        a = reg.counter("x_total", "help")
        b = reg.counter("x_total")
        assert a is b

    def test_type_clash_rejected(self, fresh_registry):
        reg = fresh_registry
        reg.counter("x")
        with pytest.raises(ValueError):
            reg.gauge("x")

    def test_render_exposition(self, fresh_registry):
        reg = fresh_registry
        reg.counter("req_total", "requests", ("code",)).labels(code="200").inc(3)
        reg.gauge("temp").set(1.5)
        h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(5.0)
        text = reg.render()
        assert "# TYPE req_total counter" in text
        assert 'req_total{code="200"} 3' in text
        assert "# TYPE temp gauge" in text
        assert "temp 1.5" in text
        assert 'lat_seconds_bucket{le="0.1"} 1' in text
        assert 'lat_seconds_bucket{le="1"} 1' in text
        assert 'lat_seconds_bucket{le="+Inf"} 2' in text
        assert "lat_seconds_count 2" in text

    def test_snapshot(self, fresh_registry):
        reg = fresh_registry
        reg.counter("a").inc(2)
        reg.histogram("h").observe(1.0)
        snap = reg.snapshot()
        assert snap["a"] == 2
        assert snap["h_count"] == 1

    def test_thread_safety_smoke(self, fresh_registry):
        c = fresh_registry.counter("c")

        def worker():
            for _ in range(1000):
                c.inc()

        ts = [threading.Thread(target=worker) for _ in range(8)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert c.value == 8000


class TestTracing:
    def test_annotate_noop(self):
        with annotate("span"):
            pass

    def test_timed_observes(self):
        h = Histogram("h")
        with timed(h, "x"):
            pass
        assert h.count == 1


class TestOplogTimestamp:
    def test_ts_round_trips(self):
        from radixmesh_tpu.cache.oplog import Oplog, OplogType, deserialize, serialize

        op = Oplog(
            op_type=OplogType.INSERT,
            origin_rank=1,
            logic_id=7,
            ttl=3,
            key=np.arange(4, dtype=np.int32),
            value=np.arange(4, dtype=np.int32),
            value_rank=1,
            ts=1234.5,
        )
        assert deserialize(serialize(op)).ts == 1234.5


class TestEngineMetrics:
    def test_engine_populates_registry(self, fresh_registry):
        from radixmesh_tpu.engine.engine import Engine
        from radixmesh_tpu.models.llama import ModelConfig, init_params
        import jax

        cfg = ModelConfig.tiny()
        params = init_params(cfg, jax.random.PRNGKey(0))
        eng = Engine(cfg, params, num_slots=512, page_size=4, max_batch=2, name="e0")
        prompt = list(range(1, 20))
        eng.generate([prompt], max_steps=30)
        eng.generate([prompt], max_steps=30)  # second pass hits the cache
        snap = fresh_registry.snapshot()
        k = '{engine="e0"}'
        assert snap[f"radixmesh_engine_prompt_tokens_total{k}"] == 2 * len(prompt)
        assert snap[f"radixmesh_engine_cached_tokens_total{k}"] > 0
        assert snap[f"radixmesh_engine_generated_tokens_total{k}"] > 0
        assert snap[f"radixmesh_engine_ttft_seconds{k}_count"] == 2
        assert snap[f"radixmesh_engine_tpot_seconds{k}_count"] >= 1
        # counter == stats (the stop-token path must not diverge)
        assert snap[f"radixmesh_engine_generated_tokens_total{k}"] == eng.stats.generated_tokens


class TestMeshMetrics:
    def test_ring_populates_lag_and_counters(self, fresh_registry):
        from radixmesh_tpu.comm.inproc import InprocHub
        from tests.test_mesh_cache import Cluster, insert_with_pool, wait_for

        InprocHub.reset_default()
        c = Cluster()
        try:
            c.wait_ready()
            prefill = c.node(1)
            insert_with_pool(prefill, [1, 2, 3])
            assert wait_for(
                lambda: all(
                    n.match_prefix([1, 2, 3]).length == 3 for n in c.ring_nodes
                )
            )
            snap = fresh_registry.snapshot()
            lag = [
                v
                for k, v in snap.items()
                if k.startswith("radixmesh_mesh_oplog_lag_seconds") and k.endswith("_count")
            ]
            assert sum(lag) > 0
            sent = [v for k, v in snap.items() if k.startswith("radixmesh_mesh_oplogs_sent")]
            assert sum(sent) > 0
            assert prefill.metrics["oplogs_sent"] > 0
            received = [
                k
                for k in snap
                if k.startswith("radixmesh_mesh_oplogs_received_total") and "INSERT" in k
            ]
            assert received
        finally:
            c.close()
            InprocHub.reset_default()


class TestExpositionStrictParse:
    """Strict parse of ``Registry.render()``: a Prometheus scrape is
    all-or-nothing — ONE malformed line poisons every series in the
    exposition — so the format contract is pinned here line by line
    (escaping round-trip, ``le`` ordering, cumulative monotonicity,
    ``_sum``/``_count`` consistency)."""

    import re as _re

    _SAMPLE = _re.compile(
        r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
        r"(?:\{(?P<labels>.*)\})?"
        r" (?P<value>[^ ]+)$"
    )

    @staticmethod
    def _parse_labels(raw: str) -> dict:
        """Char-by-char label parser honoring the exposition escapes
        (\\\\, \\", \\n) — a regex split would tear on escaped quotes."""
        labels: dict[str, str] = {}
        i = 0
        while i < len(raw):
            eq = raw.index("=", i)
            key = raw[i:eq]
            assert raw[eq + 1] == '"', raw
            j = eq + 2
            val: list[str] = []
            while raw[j] != '"':
                if raw[j] == "\\":
                    val.append({"\\": "\\", '"': '"', "n": "\n"}[raw[j + 1]])
                    j += 2
                else:
                    val.append(raw[j])
                    j += 1
            labels[key] = "".join(val)
            i = j + 1
            if i < len(raw):
                assert raw[i] == ",", raw
                i += 1
        return labels

    def _parse(self, text: str) -> list[tuple[str, dict, float]]:
        """Every non-comment line must match the sample grammar."""
        samples = []
        for line in text.splitlines():
            if not line:
                continue
            if line.startswith("#"):
                assert line.startswith(("# HELP ", "# TYPE ")), line
                continue
            m = self._SAMPLE.match(line)
            assert m is not None, f"unparseable sample line: {line!r}"
            labels = self._parse_labels(m.group("labels") or "")
            raw_v = m.group("value")
            value = float("inf") if raw_v == "+Inf" else float(raw_v)
            samples.append((m.group("name"), labels, value))
        return samples

    def test_label_escaping_round_trips(self, fresh_registry):
        reg = fresh_registry
        nasty = 'he said "hi\\there"\nand left'
        reg.counter("x_total", "t", ("who",)).labels(who=nasty).inc(3)
        samples = self._parse(reg.render())
        assert samples == [("x_total", {"who": nasty}, 3.0)]

    def test_histogram_le_ordering_and_monotonicity(self, fresh_registry):
        reg = fresh_registry
        h = reg.histogram(
            "lat_seconds", "t", ("op",), buckets=(0.1, 1.0, 10.0)
        )
        for op, values in (
            ("read", (0.05, 0.5, 0.5, 5.0, 50.0)),
            ("write", (0.01, 20.0)),
        ):
            child = h.labels(op=op)
            for v in values:
                child.observe(v)
        samples = self._parse(reg.render())
        by_series: dict[str, list[tuple[float, float]]] = {}
        for name, labels, value in samples:
            if name != "lat_seconds_bucket":
                continue
            le = labels.pop("le")
            key = repr(sorted(labels.items()))
            by_series.setdefault(key, []).append(
                (float("inf") if le == "+Inf" else float(le), value)
            )
        assert len(by_series) == 2
        for series in by_series.values():
            les = [le for le, _ in series]
            counts = [c for _, c in series]
            # le values rendered in ascending order, +Inf last...
            assert les == sorted(les) and les[-1] == float("inf")
            # ...and cumulative counts never decrease along them.
            assert counts == sorted(counts)

    def test_sum_count_consistency(self, fresh_registry):
        reg = fresh_registry
        h = reg.histogram("lat_seconds", "t", buckets=(1.0, 2.0))
        values = (0.5, 1.5, 7.0)
        for v in values:
            h.observe(v)
        samples = {
            (name, tuple(sorted(labels.items()))): value
            for name, labels, value in self._parse(reg.render())
        }
        count = samples[("lat_seconds_count", ())]
        total = samples[("lat_seconds_sum", ())]
        inf_bucket = samples[("lat_seconds_bucket", (("le", "+Inf"),))]
        assert count == len(values)
        assert count == inf_bucket  # +Inf bucket IS the count
        assert total == pytest.approx(sum(values))

    def test_every_kind_renders_parseable(self, fresh_registry):
        reg = fresh_registry
        reg.counter("a_total", "help text", ("x",)).labels(x="1").inc()
        reg.gauge("b_bytes", "gauge").set(-2.5)
        reg.histogram("c_seconds", "hist").observe(0.3)
        samples = self._parse(reg.render())  # asserts per line
        names = {name for name, _, _ in samples}
        assert {
            "a_total", "b_bytes", "c_seconds_bucket",
            "c_seconds_sum", "c_seconds_count",
        } <= names
