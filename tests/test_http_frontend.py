"""HTTP serving + routing frontends (the layer above the reference's
router that its repo explicitly leaves out — SURVEY §1 L5) and the CLI
launcher's argument surface."""

import json
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from radixmesh_tpu.engine.engine import Engine
from radixmesh_tpu.models.llama import ModelConfig, init_params
from radixmesh_tpu.server.http_frontend import RouterFrontend, ServingFrontend


def _post(url: str, obj: dict, timeout=60):
    req = urllib.request.Request(
        url,
        data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(url: str, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


@pytest.fixture(scope="module")
def frontend():
    cfg = ModelConfig.tiny()
    eng = Engine(
        cfg,
        init_params(cfg, jax.random.PRNGKey(0)),
        num_slots=512,
        page_size=4,
        max_batch=2,
        name="http-test",
    )
    f = ServingFrontend(eng, port=0)
    yield f
    f.close()


class TestServingFrontend:
    def test_generate(self, frontend):
        status, out = _post(
            f"http://127.0.0.1:{frontend.port}/generate",
            {"input_ids": list(range(1, 20)), "max_tokens": 8},
        )
        assert status == 200
        assert len(out["output_ids"]) >= 1
        assert out["cached_tokens"] == 0

    def test_generate_hits_cache_on_revisit(self, frontend):
        prompt = list(range(40, 80))
        _post(
            f"http://127.0.0.1:{frontend.port}/generate",
            {"input_ids": prompt, "max_tokens": 4},
        )
        status, out = _post(
            f"http://127.0.0.1:{frontend.port}/generate",
            {"input_ids": prompt, "max_tokens": 4},
        )
        assert status == 200
        assert out["cached_tokens"] > 0

    def test_generate_deterministic_greedy(self, frontend):
        prompt = list(range(90, 120))
        outs = [
            _post(
                f"http://127.0.0.1:{frontend.port}/generate",
                {"input_ids": prompt, "max_tokens": 6, "temperature": 0.0},
            )[1]["output_ids"]
            for _ in range(2)
        ]
        assert outs[0] == outs[1]

    def test_streaming_sse(self, frontend):
        req = urllib.request.Request(
            f"http://127.0.0.1:{frontend.port}/generate",
            data=json.dumps(
                {"input_ids": list(range(1, 16)), "max_tokens": 5, "stream": True}
            ).encode(),
            method="POST",
        )
        tokens, done = [], None
        with urllib.request.urlopen(req, timeout=60) as r:
            assert r.headers["Content-Type"].startswith("text/event-stream")
            for line in r:
                line = line.decode().strip()
                if not line.startswith("data: "):
                    continue
                evt = json.loads(line[len("data: "):])
                if evt.get("done"):
                    done = evt
                    break
                tokens.append(evt["token"])
        assert done is not None
        assert done["output_ids"] == tokens

    def test_bad_request(self, frontend):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(
                f"http://127.0.0.1:{frontend.port}/generate",
                {"input_ids": "not a list"},
            )
        assert e.value.code == 400

    def test_healthz_stats_metrics(self, frontend):
        status, _ = _get(f"http://127.0.0.1:{frontend.port}/healthz")
        assert status == 200
        status, body = _get(f"http://127.0.0.1:{frontend.port}/stats")
        assert status == 200 and b"hit_rate" in body
        # The module-scoped engine bound its counters to an earlier test's
        # registry (conftest isolates registries per test), so only check
        # the endpoint serves a well-formed exposition here; counter
        # presence is covered by test_metrics.py.
        status, body = _get(f"http://127.0.0.1:{frontend.port}/metrics")
        assert status == 200

    def test_concurrent_requests(self, frontend):
        import concurrent.futures as cf

        prompts = [list(range(s, s + 12)) for s in (1, 50, 100, 150)]
        with cf.ThreadPoolExecutor(4) as ex:
            results = list(
                ex.map(
                    lambda p: _post(
                        f"http://127.0.0.1:{frontend.port}/generate",
                        {"input_ids": p, "max_tokens": 4},
                    )[1]["output_ids"],
                    prompts,
                )
            )
        assert all(len(r) >= 1 for r in results)


class TestRouterFrontend:
    def test_route_endpoint(self):
        from radixmesh_tpu.cache.mesh_cache import MeshCache
        from radixmesh_tpu.comm.inproc import InprocHub
        from radixmesh_tpu.config import MeshConfig, NodeRole
        from radixmesh_tpu.cache.kv_pool import PagedKVPool
        from radixmesh_tpu.router.cache_aware_router import CacheAwareRouter
        import time

        InprocHub.reset_default()
        prefill, decode, router = ["p0"], ["d0"], ["r0"]
        nodes = []
        try:
            for addr in prefill + decode + router:
                cfg = MeshConfig(
                    prefill_nodes=prefill,
                    decode_nodes=decode,
                    router_nodes=router,
                    local_addr=addr,
                    protocol="inproc",
                    tick_interval_s=0.05,
                    gc_interval_s=30.0,
                )
                pool = (
                    None
                    if cfg.local_role is NodeRole.ROUTER
                    else PagedKVPool(
                        num_slots=64, num_layers=1, num_kv_heads=1, head_dim=2
                    )
                )
                nodes.append(MeshCache(cfg, pool=pool).start())
            for n in nodes:
                assert n.wait_ready(timeout=10)
            p0 = nodes[0]
            slots = p0.pool.alloc(3)
            p0.insert([7, 8, 9], slots)
            rnode = nodes[2]
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                if getattr(rnode.match_prefix([7, 8, 9]), "prefill_rank", -1) == 0:
                    break
                time.sleep(0.01)
            car = CacheAwareRouter(rnode, rnode.cfg)
            car.finish_warm_up()
            f = RouterFrontend(car, port=0)
            try:
                status, out = _post(
                    f"http://127.0.0.1:{f.port}/route", {"input_ids": [7, 8, 9, 10]}
                )
                assert status == 200
                assert out["prefill_addr"] == "p0"
                assert out["prefill_cache_hit"] is True
                assert out["match_len"] == 3
                # Cold key falls back to the hash ring.
                status, out = _post(
                    f"http://127.0.0.1:{f.port}/route", {"input_ids": [999, 998]}
                )
                assert out["prefill_addr"] == "p0"  # only node
                assert out["prefill_cache_hit"] is False
            finally:
                f.close()
        finally:
            for n in nodes:
                n.close()
            InprocHub.reset_default()


class TestLaunchCLI:
    def test_parser_surface(self):
        from radixmesh_tpu.launch import main

        with pytest.raises(SystemExit):
            main(["--help"])
        with pytest.raises(SystemExit):
            main([])  # command required

    def test_node_requires_config(self):
        from radixmesh_tpu.launch import main

        with pytest.raises(SystemExit):
            main(["node"])


class TestProfileEndpoint:
    def test_profile_captures_trace(self, tmp_path):
        import os

        cfg = ModelConfig.tiny()
        eng = Engine(cfg, init_params(cfg, jax.random.PRNGKey(0)),
                     num_slots=64, page_size=4, max_batch=1, name="http-prof")
        f = ServingFrontend(eng, port=0, profile_dir=str(tmp_path))
        try:
            code, body = _post(
                f"http://127.0.0.1:{f.port}/profile", {"seconds": 0.2}
            )
            assert code == 200, body
            assert body["logdir"].startswith(str(tmp_path))
            files = [x for _, _, fs in os.walk(body["logdir"]) for x in fs]
            assert files, "no trace artifacts written"
        finally:
            f.close()

    def test_profile_disabled_and_bad_duration(self, tmp_path, frontend):
        import urllib.error

        # fixture frontend has no profile_dir → 403
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(f"http://127.0.0.1:{frontend.port}/profile", {"seconds": 1})
        assert ei.value.code == 403
        cfg = ModelConfig.tiny()
        eng = Engine(cfg, init_params(cfg, jax.random.PRNGKey(0)),
                     num_slots=64, page_size=4, max_batch=1, name="http-prof2")
        f = ServingFrontend(eng, port=0, profile_dir=str(tmp_path))
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{f.port}/profile", {"seconds": -1})
            assert ei.value.code == 400
        finally:
            f.close()


class TestGracefulShutdown:
    def test_close_cancels_stragglers(self):
        """Waiters must not hang on requests the stopped scheduler will
        never step again: close() cancels them, so wait() returns with
        partial output and the cancelled flag set."""
        import time

        from radixmesh_tpu.engine.request import RequestState, SamplingParams

        cfg = ModelConfig.tiny()
        eng = Engine(cfg, init_params(cfg, jax.random.PRNGKey(0)),
                     num_slots=256, page_size=4, max_batch=1, name="http-drain")
        f = ServingFrontend(eng, port=0)
        req = f.runner.submit([1, 2, 3], SamplingParams(max_new_tokens=500))
        time.sleep(2.0)  # let some decoding happen
        f.close(drain_s=0.1)  # too short to finish 500 tokens
        assert len(req.generated) < 500, (
            "host decoded 500 tokens in 2s; raise max_new_tokens"
        )
        assert req.state is RequestState.FINISHED
        assert req.cancelled
        # wait() must return promptly instead of hanging on a request the
        # stopped scheduler would never finish.
        f.runner.wait(req, timeout=1.0)
        # And late submits are refused rather than stranded.
        import pytest as _pytest

        with _pytest.raises(RuntimeError):
            f.runner.submit([4, 5], SamplingParams(max_new_tokens=2))


class TestConcurrentClients:
    def test_parallel_submit_cancel_storm(self):
        """Concurrent clients through the REAL HTTP layer — /generate
        (some streaming via SSE) racing /cancel: every request must get a
        response (no stranded handler, no dropped connection) and the
        engine must drain."""
        import threading
        import time as _time

        cfg = ModelConfig.tiny()
        eng = Engine(cfg, init_params(cfg, jax.random.PRNGKey(0)),
                     num_slots=512, page_size=4, max_batch=3, name="http-conc")
        f = ServingFrontend(eng, port=0)
        errors: list = []
        rids: list = []

        def client(i):
            rng = np.random.default_rng(100 + i)  # per-thread: race-free + replayable
            try:
                n = int(rng.integers(3, 12))
                prompt = rng.integers(1, cfg.vocab_size, n).tolist()
                body = {
                    "input_ids": prompt,
                    "max_tokens": int(rng.integers(2, 10)),
                }
                if i % 4 == 1:
                    body["stream"] = True
                if i % 3 == 0:
                    # Race a cancel against the in-flight generate from a
                    # second connection (rids are assigned sequentially).
                    def late_cancel():
                        _time.sleep(0.05)
                        try:
                            _post(
                                f"http://127.0.0.1:{f.port}/cancel",
                                {"rid": i},
                            )
                        except Exception:  # noqa: BLE001 — unknown rid etc.
                            pass

                    threading.Thread(target=late_cancel).start()
                if body.get("stream"):
                    import urllib.request

                    req = urllib.request.Request(
                        f"http://127.0.0.1:{f.port}/generate",
                        data=json.dumps(body).encode(),
                        method="POST",
                    )
                    with urllib.request.urlopen(req, timeout=120) as r:
                        data = r.read().decode()  # consume the SSE stream
                    assert "done" in data
                    rids.append(i)
                else:
                    code, resp = _post(
                        f"http://127.0.0.1:{f.port}/generate", body, timeout=120
                    )
                    assert code == 200
                    rids.append(resp["rid"])
            except Exception as e:  # noqa: BLE001
                errors.append((i, repr(e)))

        threads = [threading.Thread(target=client, args=(i,)) for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        try:
            assert not errors, errors
            assert len(rids) == 12
            assert not any(t.is_alive() for t in threads), "stranded client"
            deadline = _time.monotonic() + 30
            while eng.has_work() and _time.monotonic() < deadline:
                _time.sleep(0.05)
            assert not eng.has_work()
        finally:
            f.close(drain_s=0.5)


class TestSLOFrontend:
    """The overload control plane surfaced over HTTP: tenant field,
    429 + Retry-After on rate limit, /stats slo section."""

    def _frontend(self, **tenant_kw):
        from radixmesh_tpu.slo import SLOConfig, TenantConfig

        cfg = ModelConfig.tiny()
        eng = Engine(
            cfg,
            init_params(cfg, jax.random.PRNGKey(2)),
            num_slots=512,
            page_size=4,
            max_batch=2,
            name="http-slo-test",
        )
        slo = SLOConfig(
            tenants={"free": TenantConfig(**tenant_kw)} if tenant_kw else {}
        )
        return ServingFrontend(eng, port=0, slo=slo)

    def test_generate_with_tenant_and_stats(self):
        f = self._frontend()
        try:
            status, out = _post(
                f"http://127.0.0.1:{f.port}/generate",
                {"input_ids": list(range(1, 16)), "max_tokens": 4,
                 "tenant": "pro", "ttft_deadline_ms": 60_000},
            )
            assert status == 200
            assert len(out["output_ids"]) >= 1
            status, body = _get(f"http://127.0.0.1:{f.port}/stats")
            slo = json.loads(body)["slo"]
            assert slo["total_admitted"] == 1 and slo["total_shed"] == 0
            assert "pro" in slo["tenants"]
        finally:
            f.close(drain_s=0.5)

    def test_rate_limit_answers_429_with_retry_after(self):
        # Bucket covers one 15-token prompt; near-zero refill.
        f = self._frontend(rate_tokens_per_s=0.1, burst_tokens=16)
        try:
            status, _ = _post(
                f"http://127.0.0.1:{f.port}/generate",
                {"input_ids": list(range(1, 16)), "max_tokens": 2,
                 "tenant": "free"},
            )
            assert status == 200
            with pytest.raises(urllib.error.HTTPError) as exc:
                _post(
                    f"http://127.0.0.1:{f.port}/generate",
                    {"input_ids": list(range(1, 16)), "max_tokens": 2,
                     "tenant": "free"},
                )
            err = exc.value
            assert err.code == 429
            assert int(err.headers["Retry-After"]) >= 1
            payload = json.loads(err.read())
            assert payload["shed"] and payload["reason"] == "rate_limited"
        finally:
            f.close(drain_s=0.5)

    def test_plain_frontend_ignores_slo_fields(self, frontend):
        # No control plane: tenant/deadline fields are accepted and
        # ignored (no tenants exist to enforce them against).
        status, out = _post(
            f"http://127.0.0.1:{frontend.port}/generate",
            {"input_ids": list(range(200, 220)), "max_tokens": 2,
             "tenant": "whoever", "ttft_deadline_ms": 1},
        )
        assert status == 200
        assert len(out["output_ids"]) >= 1


class TestDebugEndpoints:
    """The tracing plane's HTTP surfaces: /debug/trace (flight-recorder
    drain as Chrome trace JSON), /debug/requests (in-flight table),
    /debug/state (node snapshot) — well-formed JSON on both frontend
    variants, including under concurrent load."""

    def test_debug_state_shape(self, frontend):
        status, body = _get(f"http://127.0.0.1:{frontend.port}/debug/state")
        assert status == 200
        state = json.loads(body)
        assert state["engine"]["max_batch"] == 2
        assert state["pool"]["num_slots"] == 512
        assert state["pool"]["free_slots"] <= state["pool"]["num_slots"]
        assert "trace" in state and state["trace"]["capacity"] > 0

    def test_debug_requests_table(self, frontend):
        _post(
            f"http://127.0.0.1:{frontend.port}/generate",
            {"input_ids": list(range(300, 320)), "max_tokens": 2},
        )
        status, body = _get(f"http://127.0.0.1:{frontend.port}/debug/requests")
        assert status == 200
        table = json.loads(body)
        assert "requests" in table and isinstance(table["requests"], list)
        # Finished requests leave the table; it reports only live state.
        assert table["waiting"] == 0

    def test_debug_tokens_serves_the_token_plane(self, frontend):
        _post(
            f"http://127.0.0.1:{frontend.port}/generate",
            {"input_ids": list(range(310, 330)), "max_tokens": 4},
        )
        status, body = _get(f"http://127.0.0.1:{frontend.port}/debug/tokens")
        assert status == 200
        out = json.loads(body)
        tl = out["timeline"]
        assert tl["capacity"] > 0
        assert set(tl["stalls"].keys()) <= {
            "restore_park", "prefill_convoy", "rebalance_handoff",
            "spec_verify_miss", "scheduler_wait",
        }
        assert out["goodput"]["useful_tokens"] >= 4
        assert isinstance(out["spec"], dict)
        # ?limit= caps the recent tail; a bad limit is a 400, not a 500.
        status, body = _get(
            f"http://127.0.0.1:{frontend.port}/debug/tokens?limit=1"
        )
        assert len(json.loads(body)["timeline"]["recent"]) <= 1
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"http://127.0.0.1:{frontend.port}/debug/tokens?limit=zap")
        assert ei.value.code == 400

    def test_debug_tokens_404_when_plane_disabled(self):
        cfg = ModelConfig.tiny()
        eng = Engine(
            cfg, init_params(cfg, jax.random.PRNGKey(0)),
            num_slots=64, page_size=4, max_batch=1, name="notl",
            token_timeline_capacity=0,
        )
        f = ServingFrontend(eng, port=0)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"http://127.0.0.1:{f.port}/debug/tokens")
            assert ei.value.code == 404
        finally:
            f.close()

    def test_debug_trace_drains_chrome_json(self, frontend):
        import bench
        from radixmesh_tpu.obs.trace_plane import (
            FlightRecorder,
            set_recorder,
        )

        set_recorder(FlightRecorder(capacity=4096, sample=1.0))
        status, out = _post(
            f"http://127.0.0.1:{frontend.port}/generate",
            {"input_ids": list(range(400, 430)), "max_tokens": 3},
        )
        assert status == 200
        status, body = _get(f"http://127.0.0.1:{frontend.port}/debug/trace")
        assert status == 200
        obj = json.loads(body)
        assert bench.validate_trace(obj) == []
        names = {
            ev["name"] for ev in obj["traceEvents"] if ev.get("ph") == "X"
        }
        assert {"admission_wait", "prefill_wave", "decode_chunk",
                "publish", "http_request"} <= names
        # Default GET is a read-only snapshot (a peek must not destroy
        # the post-mortem); ?drain=1 opts into consuming the buffer.
        status, body2 = _get(f"http://127.0.0.1:{frontend.port}/debug/trace")
        assert status == 200
        obj2 = json.loads(body2)
        assert len(obj2["traceEvents"]) >= len(obj["traceEvents"])
        status, _ = _get(
            f"http://127.0.0.1:{frontend.port}/debug/trace?drain=1"
        )
        assert status == 200
        status, body3 = _get(f"http://127.0.0.1:{frontend.port}/debug/trace")
        obj3 = json.loads(body3)
        assert len(obj3["traceEvents"]) < len(obj["traceEvents"])

    def test_debug_endpoints_under_concurrent_load(self, frontend):
        import concurrent.futures as cf

        from radixmesh_tpu.obs.trace_plane import (
            FlightRecorder,
            set_recorder,
        )

        set_recorder(FlightRecorder(capacity=2048, sample=1.0))
        paths = ("/debug/trace", "/debug/requests", "/debug/state")

        def gen(i):
            return _post(
                f"http://127.0.0.1:{frontend.port}/generate",
                {"input_ids": list(range(i, i + 10)), "max_tokens": 3},
                timeout=120,
            )[0]

        def dbg(i):
            status, body = _get(
                f"http://127.0.0.1:{frontend.port}{paths[i % 3]}"
            )
            json.loads(body)  # must be well-formed under racing drains
            return status

        with cf.ThreadPoolExecutor(8) as ex:
            gens = [ex.submit(gen, 500 + 16 * i) for i in range(4)]
            dbgs = [ex.submit(dbg, i) for i in range(12)]
            assert all(f.result() == 200 for f in gens + dbgs)

    def test_router_debug_endpoints_concurrent(self):
        import concurrent.futures as cf

        import bench
        from radixmesh_tpu.cache.mesh_cache import MeshCache
        from radixmesh_tpu.comm.inproc import InprocHub
        from radixmesh_tpu.config import MeshConfig, NodeRole
        from radixmesh_tpu.cache.kv_pool import PagedKVPool
        from radixmesh_tpu.obs.trace_plane import (
            FlightRecorder,
            set_recorder,
        )
        from radixmesh_tpu.router.cache_aware_router import CacheAwareRouter

        set_recorder(FlightRecorder(capacity=4096, sample=1.0))
        InprocHub.reset_default()
        prefill, decode, router = ["p0"], ["d0"], ["r0"]
        nodes = []
        try:
            for addr in prefill + decode + router:
                cfg = MeshConfig(
                    prefill_nodes=prefill,
                    decode_nodes=decode,
                    router_nodes=router,
                    local_addr=addr,
                    protocol="inproc",
                    tick_interval_s=0.05,
                    gc_interval_s=30.0,
                )
                pool = (
                    None
                    if cfg.local_role is NodeRole.ROUTER
                    else PagedKVPool(
                        num_slots=64, num_layers=1, num_kv_heads=1, head_dim=2
                    )
                )
                nodes.append(MeshCache(cfg, pool=pool).start())
            for n in nodes:
                assert n.wait_ready(timeout=10)
            car = CacheAwareRouter(nodes[2], nodes[2].cfg)
            car.finish_warm_up()
            f = RouterFrontend(car, port=0)
            try:
                def route(i):
                    return _post(
                        f"http://127.0.0.1:{f.port}/route",
                        {"input_ids": [i, i + 1, i + 2]},
                    )[0]

                def dbg(path):
                    status, body = _get(f"http://127.0.0.1:{f.port}{path}")
                    return status, json.loads(body)

                with cf.ThreadPoolExecutor(6) as ex:
                    routes = [ex.submit(route, i) for i in range(8)]
                    assert all(r.result() == 200 for r in routes)
                status, state = dbg("/debug/state")
                assert status == 200
                assert state["router"]["warm_up"] is False
                assert state["membership"]["role"] == "router"
                assert sorted(state["router"]["alive"]["prefill"]) == ["p0"]
                status, table = dbg("/debug/requests")
                assert status == 200 and table["requests"] == []
                status, trace = dbg("/debug/trace")
                assert status == 200
                assert bench.validate_trace(trace) == []
                route_spans = [
                    ev for ev in trace["traceEvents"]
                    if ev.get("ph") == "X" and ev["name"] == "route"
                ]
                assert len(route_spans) >= 8
            finally:
                f.close()
        finally:
            for n in nodes:
                n.close()
            InprocHub.reset_default()


@pytest.mark.quick
class TestClusterEndpoints:
    """Fleet telemetry surfaces (PR 3): /cluster/health and
    /cluster/telemetry on BOTH frontends."""

    def test_serving_frontend_without_mesh(self, frontend):
        for path in ("/cluster/health", "/cluster/telemetry"):
            status, body = _get(f"http://127.0.0.1:{frontend.port}{path}")
            assert status == 200
            out = json.loads(body)
            assert out["nodes"] == {} and "note" in out

    def test_router_frontend_serves_fleet_view(self):
        import time

        from radixmesh_tpu.cache.mesh_cache import MeshCache
        from radixmesh_tpu.comm.inproc import InprocHub
        from radixmesh_tpu.config import MeshConfig, NodeRole
        from radixmesh_tpu.obs.fleet_plane import FleetPlane
        from radixmesh_tpu.router.cache_aware_router import CacheAwareRouter

        InprocHub.reset_default()
        prefill, decode, router = ["p0"], ["d0"], ["r0"]
        nodes = []
        try:
            for addr in prefill + decode + router:
                cfg = MeshConfig(
                    prefill_nodes=prefill,
                    decode_nodes=decode,
                    router_nodes=router,
                    local_addr=addr,
                    protocol="inproc",
                    tick_interval_s=0.05,
                    gc_interval_s=30.0,
                )
                nodes.append(MeshCache(cfg, pool=None).start())
            for n in nodes:
                assert n.wait_ready(timeout=10)
            planes = [
                FleetPlane(n, interval_s=0.1)
                for n in nodes
                if n.role is not NodeRole.ROUTER
            ]
            for p in planes:
                p.publish_once()
            rnode = nodes[2]
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if len(rnode.fleet.digests()) == 2:
                    break
                time.sleep(0.01)
            car = CacheAwareRouter(rnode, rnode.cfg, health_aware=True)
            car.finish_warm_up()
            f = RouterFrontend(car, port=0)
            try:
                status, body = _get(
                    f"http://127.0.0.1:{f.port}/cluster/telemetry"
                )
                assert status == 200
                tel = json.loads(body)
                assert set(tel["nodes"]) == {"0", "1"}
                assert tel["convergence"]["converged"] is True
                d = tel["nodes"]["0"]
                assert d["role"] == "prefill"
                assert len(d["fingerprint"]) == 16  # hex-encoded 64-bit
                assert tel["self"]["role"] == "router"

                status, body = _get(
                    f"http://127.0.0.1:{f.port}/cluster/health"
                )
                assert status == 200
                health = json.loads(body)
                assert health["min_score"] == 1.0
                assert set(health["nodes"]) == {"0", "1"}
                assert health["nodes"]["0"]["score"] == 1.0
                assert health["convergence"]["max_convergence_age_s"] == 0.0
            finally:
                f.close()
        finally:
            for n in nodes:
                n.close()
            InprocHub.reset_default()


class TestStitchingSurfaces:
    """PR 9 cross-node stitching HTTP seams: /generate adopts an
    upstream trace id (resume/hedge re-routes stitch under the
    originating request), and /debug/trace?format=spans serves the raw
    per-node span export the collector feeds to stitch_traces."""

    def test_generate_adopts_trace_id_and_spans_export(self, frontend):
        from radixmesh_tpu.obs.trace_plane import (
            FlightRecorder,
            set_recorder,
            stitch_traces,
        )

        set_recorder(FlightRecorder(capacity=4096, sample=1.0, node="serve"))
        status, out = _post(
            f"http://127.0.0.1:{frontend.port}/generate",
            {
                "input_ids": list(range(700, 720)),
                "max_tokens": 2,
                "trace_id": "0x00dead00beef0001",
            },
        )
        assert status == 200
        status, body = _get(
            f"http://127.0.0.1:{frontend.port}/debug/trace?format=spans"
        )
        assert status == 200
        export = json.loads(body)
        assert export["node"] == "serve"
        assert isinstance(export["wall_offset"], float)
        adopted = [
            s for s in export["spans"]
            if s["trace_id"] == "0x00dead00beef0001"
        ]
        assert adopted, "no span adopted the upstream trace id"
        assert {"prefill_wave", "publish"} <= {s["name"] for s in adopted}
        # The export stitches into a valid single-process document.
        import bench

        assert bench.validate_trace(stitch_traces([export])) == []

    def test_generate_rejects_bad_trace_id(self, frontend):
        for bad in (0, "soup"):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(
                    f"http://127.0.0.1:{frontend.port}/generate",
                    {"input_ids": [1, 2, 3], "max_tokens": 1,
                     "trace_id": bad},
                )
            assert e.value.code == 400


class TestTimeseriesAndBlackboxEndpoints:
    """PR 13 surfaces: GET /debug/timeseries (cursor pagination, both
    frontends) + POST /admin/blackbox, hammered while the sampler
    writes and while a drain-triggered black-box flush runs against
    the lifecycle plane lock (the satellite-3 concurrency contract)."""

    def test_timeseries_serves_rings_and_paginates(self, frontend):
        status, body = _get(
            f"http://127.0.0.1:{frontend.port}/debug/timeseries"
            "?family=radixmesh_history&limit=50"
        )
        assert status == 200
        page = json.loads(body)
        assert page["interval_s"] == 1.0
        # The self-accounting series exist from the first sample on.
        deadline = 50
        while not page["series"] and deadline:
            deadline -= 1
            import time as _t

            _t.sleep(0.1)
            page = json.loads(_get(
                f"http://127.0.0.1:{frontend.port}/debug/timeseries"
                "?family=radixmesh_history&limit=50"
            )[1])
        assert any(
            n.startswith("radixmesh_history_samples_total")
            for n in page["series"]
        )
        # Cursor round-trip: the next page starts past this one.
        status, body2 = _get(
            f"http://127.0.0.1:{frontend.port}/debug/timeseries"
            f"?since={page['next_since']}"
        )
        page2 = json.loads(body2)
        assert page2["since"] == page["next_since"]

    def test_timeseries_rejects_bad_cursor(self, frontend):
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(
                f"http://127.0.0.1:{frontend.port}/debug/timeseries"
                "?since=banana"
            )
        assert ei.value.code == 400

    def test_disabled_history_404s(self):
        cfg = ModelConfig.tiny()
        eng = Engine(
            cfg, init_params(cfg, jax.random.PRNGKey(0)),
            num_slots=64, page_size=4, max_batch=1, name="nohist",
        )
        f = ServingFrontend(eng, port=0, history_interval_s=0.0)
        try:
            assert f.history is None
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(f"http://127.0.0.1:{f.port}/debug/timeseries")
            assert ei.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(f"http://127.0.0.1:{f.port}/admin/blackbox", {})
            assert ei.value.code == 404
        finally:
            f.close()

    def test_admin_blackbox_flushes_a_final(self, tmp_path):
        cfg = ModelConfig.tiny()
        eng = Engine(
            cfg, init_params(cfg, jax.random.PRNGKey(0)),
            num_slots=64, page_size=4, max_batch=1, name="bb-http",
        )
        f = ServingFrontend(
            eng, port=0, history_interval_s=0.05,
            blackbox_dir=str(tmp_path),
        )
        try:
            status, res = _post(
                f"http://127.0.0.1:{f.port}/admin/blackbox", {}
            )
            assert status == 200
            assert res["flushed"] is True
            assert res["cause"] == "admin"
            import os

            assert os.path.isfile(res["path"])
            with open(res["path"]) as fh:
                final = json.load(fh)
            # The final carries the /debug/state snapshot and the live
            # doctor verdict alongside the history.
            assert final["state"]["engine"]["name"] == "bb-http"
            assert "findings" in final["doctor"]
        finally:
            f.close()

    def test_timeseries_hammered_under_sampler_and_drain_flush(
        self, tmp_path
    ):
        """The satellite-3 race: /debug/timeseries paginating from many
        threads WHILE the 20ms sampler writes the rings, WHILE requests
        generate, and WHILE a lifecycle drain (holding the plane lock)
        runs its black-box flush — no deadlock, no malformed page."""
        import concurrent.futures as cf

        from radixmesh_tpu.cache.mesh_cache import MeshCache
        from radixmesh_tpu.comm.inproc import InprocHub
        from radixmesh_tpu.config import MeshConfig
        from radixmesh_tpu.policy.lifecycle import (
            LifecycleConfig,
            LifecyclePlane,
            LifecycleState,
        )

        InprocHub.reset_default()
        cfg = ModelConfig.tiny()
        eng = Engine(
            cfg, init_params(cfg, jax.random.PRNGKey(0)),
            num_slots=512, page_size=4, max_batch=2, name="bb-drain",
        )
        mesh_nodes = []
        f = None
        lc = None
        try:
            for addr in ("hp0", "hd0"):
                mcfg = MeshConfig(
                    prefill_nodes=["hp0"],
                    decode_nodes=["hd0"],
                    router_nodes=[],
                    local_addr=addr,
                    protocol="inproc",
                    tick_interval_s=0.1,
                    failure_timeout_s=60.0,
                )
                mesh_nodes.append(MeshCache(mcfg, pool=None).start())
            for n in mesh_nodes:
                assert n.wait_ready(timeout=30)
            f = ServingFrontend(
                eng, port=0, history_interval_s=0.02,
                blackbox_dir=str(tmp_path),
            )
            lc = LifecyclePlane(
                mesh_nodes[0],
                runner=f.runner,
                blackbox=f.blackbox,
                cfg=LifecycleConfig(drain_timeout_s=10.0),
            )
            f.lifecycle = lc

            def gen(i):
                try:
                    return _post(
                        f"http://127.0.0.1:{f.port}/generate",
                        {"input_ids": list(range(i, i + 8)),
                         "max_tokens": 2},
                        timeout=60,
                    )[0]
                except urllib.error.HTTPError as e:
                    return e.code  # drain shed mid-storm is legal

            def ts(i):
                since = -1
                for _ in range(4):
                    status, body = _get(
                        f"http://127.0.0.1:{f.port}/debug/timeseries"
                        f"?since={since}&limit=200"
                    )
                    page = json.loads(body)  # well-formed under races
                    since = page["next_since"]
                return status

            def drain():
                return lc.drain(deadline_s=10.0)

            with cf.ThreadPoolExecutor(10) as ex:
                gens = [ex.submit(gen, 100 + 16 * i) for i in range(3)]
                pages = [ex.submit(ts, i) for i in range(6)]
                dr = ex.submit(drain)
                stats = dr.result(timeout=60)
                assert stats["blackbox"] is not None
                assert all(p.result(timeout=60) == 200 for p in pages)
                assert all(
                    g.result(timeout=120) in (200, 503) for g in gens
                )
            assert lc.state is LifecycleState.LEFT
            # The drain's flush landed as a complete final artifact.
            from radixmesh_tpu.obs.blackbox import load_blackbox

            dump = load_blackbox(str(tmp_path))
            assert "drain" in dump["causes"]
            assert dump["unclean"] is False
        finally:
            if lc is not None:
                lc.close()
            if f is not None:
                f.close()
            for n in mesh_nodes:
                n.close()
            InprocHub.reset_default()

    def test_router_frontend_serves_timeseries(self):
        import bench  # noqa: F401 — repo-root import convention
        from radixmesh_tpu.cache.mesh_cache import MeshCache
        from radixmesh_tpu.comm.inproc import InprocHub
        from radixmesh_tpu.config import MeshConfig
        from radixmesh_tpu.router.cache_aware_router import CacheAwareRouter

        InprocHub.reset_default()
        prefill, decode, router = ["tp0"], ["td0"], ["tr0"]
        nodes = []
        rf = None
        try:
            for addr in prefill + decode + router:
                cfg = MeshConfig(
                    prefill_nodes=prefill,
                    decode_nodes=decode,
                    router_nodes=router,
                    local_addr=addr,
                    protocol="inproc",
                    tick_interval_s=0.1,
                    failure_timeout_s=60.0,
                )
                nodes.append(MeshCache(cfg, pool=None).start())
            for n in nodes:
                assert n.wait_ready(timeout=30)
            r = CacheAwareRouter(nodes[-1], nodes[-1].cfg)
            rf = RouterFrontend(r, port=0, history_interval_s=0.02)
            deadline = 100
            page = {}
            while deadline:
                deadline -= 1
                status, body = _get(
                    f"http://127.0.0.1:{rf.port}/debug/timeseries"
                )
                page = json.loads(body)
                if page["series"]:
                    break
                import time as _t

                _t.sleep(0.05)
            assert status == 200
            assert any(
                n.startswith("radixmesh_") for n in page["series"]
            )
        finally:
            if rf is not None:
                rf.close()
            for n in nodes:
                n.close()
            InprocHub.reset_default()
