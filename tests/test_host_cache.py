"""Hierarchical KV cache: write-back to host RAM on eviction, restore on
hit (the reference's HiCache stubs — ``host_value``/``backuped``/
``host_hit_length``, ``radix_cache.py:47-61,67-84`` — made real by
``cache/host_cache.py``)."""

import jax.numpy as jnp
import numpy as np
import pytest

from radixmesh_tpu.cache.host_cache import HierarchicalCache, HostKVStore, gather_padded
from radixmesh_tpu.cache.kv_pool import PagedKVPool
from radixmesh_tpu.cache.radix_tree import RadixTree

PAGE = 4
L, H, D = 2, 2, 4


def make_pool(num_slots=32):
    return PagedKVPool(
        num_slots=num_slots, num_layers=L, num_kv_heads=H, head_dim=D,
        page_size=PAGE, dtype=jnp.float32,
    )


def make_host(num_slots=64):
    return HostKVStore(
        num_slots=num_slots, num_layers=L, num_kv_heads=H, head_dim=D,
        page_size=PAGE, dtype=jnp.float32,
    )


def fill(pool, slots, seed):
    rng = np.random.default_rng(seed)
    k = jnp.asarray(rng.normal(size=(L, len(slots), H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(L, len(slots), H, D)), jnp.float32)
    pool.write(slots, k, v)
    return np.asarray(jnp.stack([k, v]))  # [2, L, n, H, D]


class TestHostKVStore:
    def test_write_read_round_trip(self):
        host = make_host()
        slots = host.alloc(8)
        data = np.random.default_rng(0).normal(size=(2, L, 8, H, D)).astype(np.float32)
        host.write(slots[:8], data)
        np.testing.assert_array_equal(host.read(slots[:8])[0], data)

    def test_alloc_exhaustion(self):
        host = make_host(num_slots=8)
        assert host.alloc(8) is not None
        assert host.alloc(1) is None


class TestWriteback:
    def test_evict_writes_back_and_match_reports_host_tier(self):
        pool, host = make_pool(), make_host()
        tree = HierarchicalCache(pool, host)
        key = list(range(8))
        slots = pool.alloc(8)
        kv = fill(pool, slots, seed=1)
        tree.insert(key, slots)

        freed = tree.evict(8)
        assert freed == 8
        # Device slots released, node retained host-resident.
        assert pool.free_slots >= 8
        res = tree.match_prefix(key)
        assert res.length == 0
        assert res.host_length == 8
        assert res.last_host_node is not None and res.last_host_node.backuped
        # The host copy holds the same bytes the device held.
        got = host.read(res.host_indices())[0]
        np.testing.assert_allclose(got, kv, rtol=1e-6)

    def test_match_and_load_restores_device_hit(self):
        pool, host = make_pool(), make_host()
        tree = HierarchicalCache(pool, host)
        key = list(range(8))
        slots = pool.alloc(8)
        kv = fill(pool, slots, seed=2)
        tree.insert(key, slots)
        tree.evict(8)

        res = tree.match_and_load(key)
        assert res.length == 8
        assert res.host_length == 0
        restored = np.asarray(gather_padded(pool, res.indices())[0])
        np.testing.assert_allclose(restored, kv, rtol=1e-6)
        # Host copy retained: re-evicting is free (no second gather needed).
        node = res.last_node
        assert node.backuped

    def test_second_eviction_of_backed_up_node_is_free(self):
        pool, host = make_pool(), make_host()
        tree = HierarchicalCache(pool, host)
        key = list(range(8))
        tree.insert(key, pool.alloc(8))
        tree.evict(8)
        tree.match_and_load(key)
        host_before = host.free_slots
        assert tree.evict(8) == 8  # no new host allocation needed
        assert host.free_slots == host_before
        assert tree.match_prefix(key).host_length == 8

    def test_locked_nodes_not_written_back(self):
        pool, host = make_pool(), make_host()
        tree = HierarchicalCache(pool, host)
        key = list(range(8))
        tree.insert(key, pool.alloc(8))
        m = tree.match_prefix(key)
        tree.inc_lock_ref(m.last_node)
        assert tree.evict(8) == 0
        tree.dec_lock_ref(m.last_node)
        assert tree.evict(8) == 8

    def test_deep_chain_evicts_bottom_up_and_restores_in_order(self):
        pool, host = make_pool(num_slots=64), make_host(num_slots=64)
        tree = HierarchicalCache(pool, host)
        kvs = {}
        k1, k2 = list(range(8)), list(range(12))
        s1 = pool.alloc(8)
        kvs[1] = fill(pool, s1, 3)
        tree.insert(k1, s1)
        s2 = pool.alloc(4)
        kvs[2] = fill(pool, s2, 4)
        tree.insert(k2, np.concatenate([s1, s2]))

        tree.evict(12)  # both nodes written back, deepest (LRU-wise) first
        assert tree.match_prefix(k2).host_length == 12
        res = tree.match_and_load(k2)
        assert res.length == 12
        got = gather_padded(pool, res.indices())[0]
        np.testing.assert_allclose(got[:, :, :8], kvs[1], rtol=1e-6)
        np.testing.assert_allclose(got[:, :, 8:], kvs[2], rtol=1e-6)


class TestHostPressure:
    def test_host_arena_full_falls_back_to_plain_eviction(self):
        pool, host = make_pool(num_slots=32), make_host(num_slots=8)
        tree = HierarchicalCache(pool, host)
        tree.insert(list(range(8)), pool.alloc(8))
        tree.insert([99] * 4 + [98] * 4, pool.alloc(8))
        tree.evict(16)
        # Host holds 8 of the 16 evicted tokens; the other node dropped (or
        # displaced the first): either way nothing crashed and at most 8
        # tokens are host-resident.
        total_host = sum(
            len(n.host_value)
            for n in tree._all_nodes()
            if n.host_value is not None
        )
        assert total_host <= 8
        assert pool.free_slots >= 16

    def test_partial_restore_when_device_pool_tight(self):
        pool, host = make_pool(num_slots=16), make_host(num_slots=32)
        tree = HierarchicalCache(pool, host)
        key = list(range(16))
        tree.insert(key, pool.alloc(16))
        tree.evict(16)
        # Occupy most of the pool so restore can only partially succeed.
        blocker = pool.alloc(12)
        assert blocker is not None
        res = tree.match_and_load(key)
        assert res.length == 4  # one page restored
        assert res.length + tree.match_prefix(key).host_length == 16


class TestPlainTreeUnaffected:
    def test_base_tree_eviction_still_removes(self):
        pool = make_pool()
        tree = RadixTree(page_size=PAGE, on_free=pool.free)
        tree.insert(list(range(8)), pool.alloc(8))
        assert tree.evict(8) == 8
        assert tree.match_prefix(list(range(8))).length == 0
        assert tree.match_prefix(list(range(8))).host_length == 0


class TestEngineWithHostTier:
    def test_engine_serves_hits_after_hbm_pressure(self):
        """A prefix forced out of the (tiny) device pool by a second
        request still produces a cache hit on re-arrival, restored from
        host RAM."""
        import jax

        from radixmesh_tpu.engine.engine import Engine
        from radixmesh_tpu.models.llama import ModelConfig, init_params

        cfg = ModelConfig.tiny()
        eng = Engine(
            cfg,
            init_params(cfg, jax.random.PRNGKey(0)),
            num_slots=128,
            page_size=4,
            max_batch=1,
            max_seq_len=96,
            host_cache_slots=1024,
            name="hicache-test",
        )
        a = list(range(1, 60))
        b = list(range(100, 160))
        eng.generate([a], max_steps=30)
        eng.generate([b], max_steps=30)  # evicts much of a's KV to host
        eng.generate([a], max_steps=30)  # must hit via host restore
        assert eng.stats.cached_tokens > 0
        from radixmesh_tpu.obs.metrics import get_registry

        snap = get_registry().snapshot()
        assert snap.get("radixmesh_hicache_backup_tokens_total", 0) > 0
        assert snap.get("radixmesh_hicache_restore_tokens_total", 0) > 0


class TestDeviceClosureInvariant:
    def test_insert_readopts_host_resident_span(self):
        """Publishing a recomputed sequence through a written-back prefix
        re-adopts device KV into the host-resident nodes: the whole path
        becomes device-resident again (no device leaf stranded below a
        host node), and the adopted span is NOT reported already-present
        (its slots are tree-owned now)."""
        pool, host = make_pool(), make_host()
        tree = HierarchicalCache(pool, host)
        k8 = list(range(8))
        tree.insert(k8, pool.alloc(8))
        tree.evict(8)
        assert tree.match_prefix(k8).host_length == 8

        # Recompute: fresh device slots for the full 12-token sequence.
        k12 = list(range(12))
        slots = pool.alloc(12)
        fill(pool, slots, seed=9)
        matched = tree.insert(k12, slots)
        assert matched == 0  # adopted spans are not "already present"
        res = tree.match_prefix(k12)
        assert res.length == 12 and res.host_length == 0
        np.testing.assert_array_equal(res.indices(), slots)
        # Accounting: the full path is evictable again.
        assert tree.evictable_size() == 12

    def test_evict_skips_host_parent_to_device_ancestor(self):
        """R → A(dev) → H(host-only) → C(dev): one evict() call must free
        both C and A (H, holding no device KV, is transparent)."""
        pool, host = make_pool(num_slots=64), make_host()
        tree = HierarchicalCache(pool, host)
        sA = pool.alloc(4)
        tree.insert(list(range(4)), sA)
        sH = pool.alloc(4)
        tree.insert(list(range(8)), np.concatenate([sA, sH]))
        sC = pool.alloc(4)
        tree.insert(list(range(12)), np.concatenate([sA, sH, sC]))
        # Make the middle node host-only by hand (simulating an earlier
        # partial restore state).
        res = tree.match_prefix(list(range(8)))
        h_node = res.last_node
        assert len(h_node.key) == 4
        hs = host.alloc(4)
        host.write(hs, *gather_padded(pool, np.asarray(h_node.value)))
        pool.free(np.asarray(h_node.value))
        h_node.host_value = hs
        h_node.value = None
        tree.evictable_size_ -= 4

        freed = tree.evict(8)  # C then A, skipping H
        assert freed == 8
        assert pool.free_slots >= 8


class TestQuantizedHostTier:
    """Quantized pools back up and restore their raw int8 + scales: a
    quarter of the dequantized host bytes and bit-exact round trips."""

    def test_writeback_restore_round_trip_int8(self):
        pool = PagedKVPool(num_slots=64, num_layers=L, num_kv_heads=H,
                           head_dim=D, page_size=PAGE, quant="int8")
        host = HostKVStore(num_slots=64, num_layers=L, num_kv_heads=H,
                           head_dim=D, page_size=PAGE, quant="int8")
        assert host._arena.dtype == np.int8 and host._scale_arena is not None
        tree = HierarchicalCache(pool, host)
        key = list(range(8))
        slots = pool.alloc(8)
        rng = np.random.default_rng(5)
        k = jnp.asarray(rng.normal(size=(L, 8, H, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(L, 8, H, D)), jnp.float32)
        pool.write(slots, k, v)
        stored_kv, stored_sc = pool.gather_raw(slots)
        stored_kv, stored_sc = np.asarray(stored_kv), np.asarray(stored_sc)
        tree.insert(key, slots)

        tree.evict(8)
        assert tree.match_prefix(key).host_length == 8

        res = tree.match_and_load(key)
        assert res.length == 8
        back_kv, back_sc = pool.gather_raw(res.indices())
        np.testing.assert_array_equal(np.asarray(back_kv), stored_kv)
        np.testing.assert_array_equal(np.asarray(back_sc), stored_sc)


class TestRestoreOverlap:
    """VERDICT round-3 next-step #7: restores must be DISPATCHED during
    admission ahead of the group's prefill (JAX async dispatch = the
    device drains the copies while the host builds prefill arrays), and
    the blocking host-side cost must surface as a /metrics histogram."""

    def test_restores_dispatch_before_group_prefill(self):
        import jax

        from radixmesh_tpu.engine.engine import Engine
        from radixmesh_tpu.models.llama import ModelConfig, init_params

        cfg = ModelConfig.tiny()
        eng = Engine(
            cfg,
            init_params(cfg, jax.random.PRNGKey(0)),
            num_slots=96,
            page_size=4,
            max_batch=2,
            max_seq_len=96,
            host_cache_slots=2048,
            name="hicache-overlap",
        )
        from radixmesh_tpu.engine.request import SamplingParams

        short = SamplingParams(temperature=0.0, max_new_tokens=4)
        a = list(range(1, 60))
        b = list(range(100, 160))
        eng.generate([a], short, max_steps=40)
        eng.generate([b], short, max_steps=40)  # pressure: a's KV → host

        events: list[str] = []
        orig_read = eng.tree.host.read
        orig_group = eng._prefill_group
        orig_dense = eng._prefill_dense
        orig_admit = eng._admit
        eng.tree.host.read = lambda *x, **k: (
            events.append("restore"), orig_read(*x, **k)
        )[1]

        def spy_group(group):
            events.append("prefill")
            return orig_group(group)

        def spy_dense(*x):
            events.append("prefill")
            return orig_dense(*x)

        def spy_admit():
            events.append("admit")
            return orig_admit()

        eng._prefill_group = spy_group
        eng._prefill_dense = spy_dense
        eng._admit = spy_admit
        try:
            # Re-arrival of `a` needs a host restore; a fresh request
            # prefills alongside it.
            eng.generate([a, list(range(200, 240))], short, max_steps=80)
        finally:
            eng.tree.host.read = orig_read
            eng._prefill_group = orig_group
            eng._prefill_dense = orig_dense
            eng._admit = orig_admit
        assert "restore" in events and "prefill" in events, events
        # Within every admission round, restore dispatches precede the
        # round's first prefill launch: by the time prefill (behind the
        # restores in the device queue) builds+runs, the copies are
        # already streaming — that's the overlap window.
        rounds: list[list[str]] = []
        for e in events:
            if e == "admit":
                rounds.append([])
            elif rounds:
                rounds[-1].append(e)
        both = [r for r in rounds if "restore" in r and "prefill" in r]
        assert both, (events, rounds)
        for r in both:
            assert r.index("restore") < r.index("prefill"), rounds
        # The blocking host-side cost surfaced in /metrics.
        from radixmesh_tpu.obs.metrics import get_registry

        reg = get_registry()
        snap = reg.snapshot()
        stall_counts = [
            v for k, v in snap.items()
            if k.startswith("radixmesh_hicache_restore_stall_seconds")
            and k.endswith("_count")
        ]
        assert stall_counts and sum(stall_counts) >= 1, sorted(
            k for k in snap if k.startswith("radixmesh_hicache")
        )
        assert "radixmesh_hicache_restore_stall_seconds" in reg.render()


@pytest.mark.quick
class TestBatchedWritebackSweep:
    """PR 4 satellite: eviction write-back is SWEEP-batched — one fused
    device gather per sweep regardless of how many nodes it absorbs
    (the seed paid one gather_padded, and one device sync, per node)."""

    def _tree_with_chains(self, n_chains=4, chain_len=8, quant=None):
        pool = PagedKVPool(num_slots=256, num_layers=L, num_kv_heads=H,
                           head_dim=D, page_size=PAGE,
                           dtype=jnp.float32, quant=quant)
        host = HostKVStore(num_slots=256, num_layers=L, num_kv_heads=H,
                           head_dim=D, page_size=PAGE,
                           dtype=jnp.float32, quant=quant)
        tree = HierarchicalCache(pool, host)
        keys, raws = [], []
        rng = np.random.default_rng(9)
        for i in range(n_chains):
            key = list(range(100 * i, 100 * i + chain_len))
            slots = pool.alloc(chain_len)
            k = jnp.asarray(rng.normal(size=(L, chain_len, H, D)), jnp.float32)
            v = jnp.asarray(rng.normal(size=(L, chain_len, H, D)), jnp.float32)
            pool.write(slots, k, v)
            raw_kv, raw_sc = pool.gather_raw(slots)
            raws.append((np.asarray(raw_kv),
                         None if raw_sc is None else np.asarray(raw_sc)))
            tree.insert(key, slots)
            keys.append(key)
        return tree, keys, raws

    def test_one_gather_per_sweep_many_nodes(self):
        tree, keys, _ = self._tree_with_chains(n_chains=5)
        freed = tree.evict(1000)
        assert freed == 5 * 8
        assert tree.wb_sweeps == 1
        assert tree.wb_gathers == 1  # fused: NOT one per node
        for key in keys:
            assert tree.match_prefix(key).host_length == 8

    @pytest.mark.parametrize("quant", [None, "int8"])
    def test_sweep_round_trip_bitwise_equivalence(self, quant):
        """Property: evict (fused sweep) → host → restore → the pool
        holds the exact stored representation again, fp and int8 raw
        paths — identical attention inputs, hence identical outputs."""
        tree, keys, raws = self._tree_with_chains(n_chains=4, quant=quant)
        tree.evict(1000)
        assert tree.wb_gathers == 1
        for key, (raw_kv, raw_sc) in zip(keys, raws):
            res = tree.match_and_load(key)
            assert res.length == len(key)
            back_kv, back_sc = tree.pool.gather_raw(res.indices())
            np.testing.assert_array_equal(np.asarray(back_kv), raw_kv)
            if quant is not None:
                np.testing.assert_array_equal(np.asarray(back_sc), raw_sc)

    @pytest.mark.parametrize("quant", [None, "int8"])
    def test_async_plane_sweep_round_trip(self, quant):
        """The same property with the write-back materialized on the
        plane worker: wait_host_ready is the arena read barrier."""
        from radixmesh_tpu.cache.kv_transfer import KVTransferPlane

        tree, keys, raws = self._tree_with_chains(n_chains=3, quant=quant)
        plane = KVTransferPlane(name=f"wbtest-{quant}")
        tree.plane = plane
        try:
            tree.evict(1000)
            assert tree.wb_gathers == 1
            assert plane.wait_host_ready()
            for key, (raw_kv, raw_sc) in zip(keys, raws):
                res = tree.match_and_load(key)
                assert res.length == len(key)
                back_kv, back_sc = tree.pool.gather_raw(res.indices())
                np.testing.assert_array_equal(np.asarray(back_kv), raw_kv)
                if quant is not None:
                    np.testing.assert_array_equal(np.asarray(back_sc), raw_sc)
        finally:
            plane.close()
